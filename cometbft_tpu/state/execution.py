"""BlockExecutor: create/validate/execute blocks against the ABCI app.

Parity with reference state/execution.go: CreateProposalBlock (:114),
ProcessProposal (:177), ValidateBlock (:205) with the fork's
last-validated-block cache + block-time tolerance (:44-52,:261-274),
ApplyBlock / ApplyVerifiedBlock (:258,:246), Commit + mempool update
(:446-509), updateState (:694), fireEvents (:766).
"""

from __future__ import annotations

import hashlib
import time
from typing import List, Optional, Tuple

from .. import types as T
from ..abci import types as abci
from ..crypto import merkle
from ..types import events as ev
from ..utils import codec, proto
from ..utils.fail import fail_point
from . import native_finalize
from .state_types import BLOCK_VERSION, State
from .validation import validate_block

# fork feature: tolerate proposer clocks slightly ahead (execution.go:44)
# Opt-in like the reference (state/validation.go:124 checks only tol > 0):
# 0 disables the wall-clock check so historical catch-up (blocksync /
# replay) is never rejected for "future" timestamps.
DEFAULT_BLOCK_TIME_TOLERANCE_NS = 0


def results_hash(tx_results: List[abci.ExecTxResult]) -> bytes:
    # bftlint: disable-next=ASY123 — portable twin of the native lane; the finalize path reads artifacts.results_hash, this serves compat callers (light proxy, replay) on short lists
    return merkle.hash_from_byte_slices([r.encode() for r in tx_results])


def _enc_abci_event(e: abci.Event) -> bytes:
    out = proto.field_string(1, e.type_)
    for a in e.attributes:
        k, v, idx = abci.attr_kvi(a)  # bftlint: disable=ASY123 — portable event encoder: the finalize path carries pre-encoded artifacts; this serves no-artifact callers (decode roundtrips, tests)
        out += proto.field_bytes(
            2,
            proto.field_string(1, k)
            + proto.field_string(2, v)
            + proto.field_varint(3, 1 if idx else 0),
        )
    return out


def _dec_abci_event(b: bytes) -> abci.Event:
    m = proto.parse(b)
    attrs = []
    for ab in m.get(2, []):
        am = proto.parse(ab)
        attrs.append(
            abci.EventAttribute(
                key=proto.get1(am, 1, b"").decode(),
                value=proto.get1(am, 2, b"").decode(),
                index=bool(proto.get1(am, 3, 0)),
            )
        )
    return abci.Event(type_=proto.get1(m, 1, b"").decode(), attributes=attrs)


def encode_finalize_response(
    resp: abci.ResponseFinalizeBlock, artifacts=None
) -> bytes:
    # NOTE: per-tx events ride NEW fields (4: block events, 5: one
    # aligned event-list per tx_result) because r.encode() feeds
    # LastResultsHash and must stay byte-stable (ISSUE 15: the stored
    # response is the indexer's crash-replay source — events lost
    # here would be index rows lost to a crash). Old records simply
    # lack fields 4/5 and decode event-less, as before.
    #
    # When the finalize pass already ran, ``artifacts`` carries the
    # result/event bytes encoded once for LastResultsHash — fields
    # 1/4/5 reuse them instead of re-encoding (byte-identical: the
    # portable twin is differential-tested against both encoders).
    out = b""
    if artifacts is not None:
        for rb in artifacts.results_enc:
            out += proto.field_message(1, rb)
    else:
        for r in resp.tx_results:
            out += proto.field_message(1, r.encode())  # bftlint: disable=ASY123 — no-artifacts fallback (tests/compat); apply_hash_persist always passes artifacts
    for vu in resp.validator_updates:
        out += proto.field_message(
            2,
            proto.field_string(1, vu.pub_key_type)
            + proto.field_bytes(2, vu.pub_key_bytes)
            + proto.field_varint(3, vu.power),
        )
    out += proto.field_bytes(3, resp.app_hash)
    if artifacts is not None:
        for eb in artifacts.block_events_enc:
            out += proto.field_message(4, eb)
        for i, evs in enumerate(artifacts.tx_events_enc):
            if not evs:
                continue  # empty fields encode to nothing; key by index
            out += proto.field_message(
                5,
                proto.field_varint(1, i)
                + b"".join(proto.field_message(2, eb) for eb in evs),
            )
        return out
    for e in resp.events:
        out += proto.field_message(4, _enc_abci_event(e))  # bftlint: disable=ASY123 — no-artifacts fallback (tests/compat); apply_hash_persist always passes artifacts
    for i, r in enumerate(resp.tx_results):
        if not r.events:
            continue  # empty fields encode to nothing; key by index
        out += proto.field_message(
            5,
            proto.field_varint(1, i)
            + b"".join(
                proto.field_message(2, _enc_abci_event(e))  # bftlint: disable=ASY123 — no-artifacts fallback (tests/compat); apply_hash_persist always passes artifacts
                for e in r.events
            ),
        )
    return out


def decode_finalize_response(b: bytes) -> abci.ResponseFinalizeBlock:
    m = proto.parse(b)
    txrs = []
    for rb in m.get(1, []):
        rm = proto.parse(rb)
        txrs.append(
            abci.ExecTxResult(
                code=proto.get1(rm, 1, 0),
                data=proto.get1(rm, 2, b""),
                gas_wanted=proto.get1(rm, 5, 0),
                gas_used=proto.get1(rm, 6, 0),
                codespace=proto.get1(rm, 8, b"").decode() if proto.get1(rm, 8) else "",
            )
        )
    for evb in m.get(5, []):
        em = proto.parse(evb)
        i = proto.get1(em, 1, 0)
        if 0 <= i < len(txrs):
            txrs[i].events = [
                _dec_abci_event(eb) for eb in em.get(2, [])
            ]
    vus = []
    for vb in m.get(2, []):
        vm = proto.parse(vb)
        vus.append(
            abci.ValidatorUpdate(
                pub_key_type=proto.get1(vm, 1, b"").decode(),
                pub_key_bytes=proto.get1(vm, 2, b""),
                power=proto.get1(vm, 3, 0),
            )
        )
    return abci.ResponseFinalizeBlock(
        events=[_dec_abci_event(eb) for eb in m.get(4, [])],
        tx_results=txrs,
        validator_updates=vus,
        app_hash=proto.get1(m, 3, b""),
    )


def build_last_commit_info(lc, last_vals) -> Optional[abci.CommitInfo]:
    """CommitInfo for a block's carried last-commit (reference
    state/execution.go buildLastCommitInfo): one VoteInfo per validator
    of height-1, flagged by participation — apps use this for reward
    distribution."""
    if lc is None or last_vals is None or not lc.signatures:
        return None
    votes = []
    for i, v in enumerate(last_vals.validators):
        flag = abci.BLOCK_ID_FLAG_ABSENT
        if i < len(lc.signatures):
            flag = lc.signatures[i].block_id_flag
        votes.append(
            abci.VoteInfo(
                validator_address=v.address,
                power=v.voting_power,
                block_id_flag=flag,
            )
        )
    return abci.CommitInfo(round=lc.round, votes=votes)


def build_extended_commit_info(ec, last_vals):
    """ExtendedCommitInfo for PrepareProposal when vote extensions are
    enabled (reference state/execution.go buildExtendedCommitInfo)."""
    if ec is None or last_vals is None:
        return None
    votes = []
    for i, v in enumerate(last_vals.validators):
        flag = abci.BLOCK_ID_FLAG_ABSENT
        ext = ext_sig = b""
        if i < len(ec.extended_signatures):
            s = ec.extended_signatures[i]
            flag = s.block_id_flag
            if flag == abci.BLOCK_ID_FLAG_COMMIT:
                # extension payloads only ride COMMIT lanes (ABCI
                # contract; defensive against a non-conforming EC)
                ext, ext_sig = s.extension, s.extension_signature
        votes.append(
            abci.ExtendedVoteInfo(
                validator_address=v.address,
                power=v.voting_power,
                block_id_flag=flag,
                vote_extension=ext,
                extension_signature=ext_sig,
            )
        )
    return abci.ExtendedCommitInfo(round=ec.round, votes=votes)


def evidence_to_misbehavior(evidence) -> List[abci.Misbehavior]:
    """ABCI Misbehavior records from block evidence (reference
    types/evidence.go ABCI() — duplicate votes map 1:1, a light-client
    attack yields one record per byzantine validator)."""
    from ..evidence.types import (
        DuplicateVoteEvidence,
        LightClientAttackEvidence,
    )

    out = []
    for e in evidence:
        if isinstance(e, DuplicateVoteEvidence):
            out.append(
                abci.Misbehavior(
                    type_=abci.MISBEHAVIOR_DUPLICATE_VOTE,
                    validator_address=e.vote_a.validator_address,
                    validator_power=e.validator_power,
                    height=e.height(),
                    time_ns=e.timestamp_ns,
                    total_voting_power=e.total_voting_power,
                )
            )
        elif isinstance(e, LightClientAttackEvidence):
            for v in e.byzantine_validators:
                out.append(
                    abci.Misbehavior(
                        type_=abci.MISBEHAVIOR_LIGHT_CLIENT_ATTACK,
                        validator_address=v.address,
                        validator_power=v.voting_power,
                        height=e.common_height,
                        time_ns=e.timestamp_ns,
                        total_voting_power=e.total_voting_power,
                    )
                )
    return out


class BlockExecutor:
    def __init__(
        self,
        state_store,
        proxy_consensus,
        mempool,
        evidence_pool=None,
        event_bus: Optional[ev.EventBus] = None,
        block_store=None,
        signature_cache: Optional[T.SignatureCache] = None,
        block_time_tolerance_ns: int = DEFAULT_BLOCK_TIME_TOLERANCE_NS,
    ):
        self.store = state_store
        self.proxy = proxy_consensus
        self.mempool = mempool
        self.evpool = evidence_pool
        self.event_bus = event_bus
        self.block_store = block_store
        self.sig_cache = signature_cache or T.SignatureCache()
        self.tolerance_ns = block_time_tolerance_ns
        # fork feature: skip re-validating a block we already validated
        self._last_validated: Optional[bytes] = None
        # set by metrics: fn(seconds) per applied block
        self.block_processing_observer = None

    # --- proposal creation (reference :114) ---------------------------

    def extend_vote(
        self, block_hash: bytes, height: int, round_: int, time_ns: int
    ) -> bytes:
        """App-provided vote extension for our own precommit
        (reference state/execution.go ExtendVote -> ABCI ExtendVote)."""
        resp = self.proxy.extend_vote(
            abci.RequestExtendVote(
                hash=block_hash,
                height=height,
                round=round_,
                time_ns=time_ns,
            )
        )
        return resp.vote_extension or b""

    def verify_vote_extension(self, vote) -> bool:
        """App acceptance of a peer's vote extension (reference
        VerifyVoteExtension; rejection rejects the whole precommit)."""
        try:
            resp = self.proxy.verify_vote_extension(
                abci.RequestVerifyVoteExtension(
                    hash=vote.block_id.hash or b"",
                    validator_address=vote.validator_address,
                    height=vote.height,
                    vote_extension=vote.extension,
                )
            )
        except Exception:
            return False
        return resp.status == abci.VERIFY_VOTE_EXT_ACCEPT

    def create_proposal_block(
        self,
        height: int,
        state: State,
        last_commit: Optional[T.Commit],
        proposer_addr: bytes,
        time_ns: Optional[int] = None,
        extended_commit: Optional[T.ExtendedCommit] = None,
    ) -> Tuple[T.Block, T.PartSet]:
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence = (
            self.evpool.pending_evidence(
                state.consensus_params.evidence.max_bytes
            )
            if self.evpool
            else []
        )
        txs = self.mempool.reap_max_bytes_max_gas(
            max_bytes - 2048, max_gas
        )
        t = time_ns or time.time_ns()
        if extended_commit is not None:
            # extensions enabled at height-1: the app sees the
            # extension payloads (reference buildExtendedCommitInfo)
            lci = build_extended_commit_info(
                extended_commit, state.last_validators
            )
        else:
            lci = build_last_commit_info(last_commit, state.last_validators)
        req = abci.RequestPrepareProposal(
            max_tx_bytes=max_bytes - 2048,
            txs=txs,
            local_last_commit=lci,
            misbehavior=evidence_to_misbehavior(evidence),
            height=height,
            time_ns=t,
            next_validators_hash=state.next_validators.hash(),
            proposer_address=proposer_addr,
        )
        resp = self.proxy.prepare_proposal(req)
        block = self._make_block(
            height, state, resp.txs, last_commit, evidence, proposer_addr, t
        )
        ps = T.PartSet.from_data(codec.encode_block(block))
        return block, ps

    def _make_block(
        self, height, state, txs, last_commit, evidence, proposer_addr, t
    ) -> T.Block:
        data = T.Data(txs=list(txs))
        ev_hash = merkle.hash_from_byte_slices([e.hash() for e in evidence])
        header = T.Header(
            version_block=BLOCK_VERSION,
            chain_id=state.chain_id,
            height=height,
            time_ns=t,
            last_block_id=state.last_block_id,
            last_commit_hash=last_commit.hash() if last_commit else b"",
            data_hash=data.hash(),
            validators_hash=state.validators.hash(),
            next_validators_hash=state.next_validators.hash(),
            consensus_hash=state.consensus_params.hash(),
            app_hash=state.app_hash,
            last_results_hash=state.last_results_hash,
            evidence_hash=ev_hash,
            proposer_address=proposer_addr,
        )
        return T.Block(
            header=header, data=data, evidence=evidence, last_commit=last_commit
        )

    # --- proposal processing (reference :177) -------------------------

    def process_proposal(self, block: T.Block, state: State) -> bool:
        req = abci.RequestProcessProposal(
            txs=block.data.txs,
            proposed_last_commit=build_last_commit_info(
                block.last_commit, state.last_validators
            ),
            misbehavior=evidence_to_misbehavior(block.evidence),
            hash=block.hash(),
            height=block.height,
            time_ns=block.header.time_ns,
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        )
        return self.proxy.process_proposal(req).is_accepted()

    # --- validation (reference :205) ----------------------------------

    def validate_block(
        self,
        state: State,
        block: T.Block,
        skip_commit_check: bool = False,
        priority=None,
    ) -> None:
        bh = block.hash()
        if self._last_validated == bh:
            return  # fork: last-validated-block cache (execution.go:261)
        validate_block(
            state, block, cache=self.sig_cache,
            skip_commit_check=skip_commit_check,
            priority=priority,
        )
        # block-time tolerance: reject blocks too far in the future
        # (only when enabled, reference state/validation.go:124)
        if (
            self.tolerance_ns > 0
            and block.header.time_ns > time.time_ns() + self.tolerance_ns
        ):
            raise ValueError("block timestamp too far in the future")
        self._last_validated = bh

    # --- execution (reference :258-446) -------------------------------

    def apply_block(
        self, state: State, block_id: T.BlockID, block: T.Block,
        verified: bool = False,
    ) -> State:
        t0 = time.monotonic()
        resp = self.apply_finalize(state, block, verified=verified)
        new_state, artifacts = self.apply_hash_persist(
            state, block_id, block, resp
        )
        return self.apply_complete(
            new_state, block_id, block, resp, artifacts, t0
        )

    # The three finalize phases. The serial apply_block above is their
    # sequential composition — same order, same fail points. The
    # pipelined path (consensus/state.py _start_pipelined_finalize)
    # splits at the phase seams instead: apply_finalize stays on-loop
    # (ABCI dispatch is app-owned and GIL-ful), apply_hash_persist
    # rides asyncio.to_thread (the native finalize pass releases the
    # GIL for the hash/encode leg and sqlite releases it for the
    # write), apply_complete lands back on-loop (mempool lock, event
    # bus, observers).

    def apply_finalize(
        self, state: State, block: T.Block, verified: bool = False
    ) -> abci.ResponseFinalizeBlock:
        """Phase 1 (on-loop): validate + ABCI FinalizeBlock."""
        if not verified:
            self.validate_block(state, block)
        req = abci.RequestFinalizeBlock(
            txs=block.data.txs,
            decided_last_commit=build_last_commit_info(
                block.last_commit, state.last_validators
            ),
            misbehavior=evidence_to_misbehavior(block.evidence),
            hash=block.hash(),
            height=block.height,
            time_ns=block.header.time_ns,
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        )
        resp = self.proxy.finalize_block(req)
        fail_point("exec-after-finalize")  # reference execution.go:313
        if len(resp.tx_results) != len(block.data.txs):
            raise RuntimeError("app returned wrong number of tx results")
        return resp

    def apply_hash_persist(
        self, state: State, block_id: T.BlockID, block: T.Block, resp
    ):
        """Phase 2 (thread-ridable): one native finalize pass — per-tx
        sha256, ExecTxResult encodes, LastResultsHash, event encodes —
        then the stored response + state save reusing those bytes."""
        artifacts = native_finalize.finalize_pass(block.data.txs, resp)
        self.store.save_finalize_block_response(
            block.height, encode_finalize_response(resp, artifacts)
        )
        fail_point("exec-after-save-response")  # :320
        new_state = self._update_state(
            state, block_id, block, resp, artifacts
        )
        return new_state, artifacts

    def apply_complete(
        self,
        new_state: State,
        block_id: T.BlockID,
        block: T.Block,
        resp,
        artifacts=None,
        t0: Optional[float] = None,
    ) -> State:
        """Phase 3 (on-loop): commit, evidence, prune, events."""
        self._commit(new_state, block, resp)
        if self.evpool:
            self.evpool.update(new_state, block.evidence)
        self._prune(new_state)
        self._fire_events(block, block_id, resp, artifacts)
        # observability hook (reference state/execution.go:292
        # BlockProcessingTime metric)
        if self.block_processing_observer is not None and t0 is not None:
            try:
                self.block_processing_observer(time.monotonic() - t0)
            except Exception:
                pass
        return new_state

    def apply_verified_block(
        self, state: State, block_id: T.BlockID, block: T.Block
    ) -> State:
        """Skip validation: commit already verified (blocksync/ingest,
        reference :246)."""
        return self.apply_block(state, block_id, block, verified=True)

    def _commit(self, state: State, block: T.Block, resp) -> None:
        self.mempool.lock()
        try:
            fail_point("exec-before-abci-commit")  # :355
            cres = self.proxy.commit()
            fail_point("exec-after-abci-commit")  # :363
            self.mempool.update(
                block.height, block.data.txs, resp.tx_results
            )
            self._retain_height = getattr(cres, "retain_height", 0)
        finally:
            self.mempool.unlock()

    def _prune(self, state: State) -> None:
        rh = getattr(self, "_retain_height", 0)
        hook = getattr(self, "retention_hook", None)
        if hook is not None:
            # the retention plane owns pruning (store/retention.py):
            # record the app's retain_height and return — deletes run
            # on the plane's cadence, in bounded batches, OFF this
            # consensus path (the legacy inline path below was an
            # unbounded scan on the commit critical path)
            if rh:
                try:
                    hook(rh)
                except Exception:
                    pass
            return
        if rh and self.block_store is not None:
            try:
                self.block_store.prune_blocks(rh)
                self.store.prune_states(rh)
            except Exception:
                pass

    def _update_state(
        self, state: State, block_id: T.BlockID, block: T.Block, resp,
        artifacts=None,
    ) -> State:
        nvals = state.next_validators.copy()
        changed = state.last_height_validators_changed
        if resp.validator_updates:
            changes = []
            from ..crypto.keys import pubkey_from_type_bytes

            for vu in resp.validator_updates:
                pk = pubkey_from_type_bytes(vu.pub_key_type, vu.pub_key_bytes)
                changes.append(T.Validator(pk, vu.power))
            nvals.update_with_change_set(changes)
            # updates from block H take effect at H+2 (reference
            # state/execution.go:713, header.Height + 1 + 1) — also the
            # height whose S:vi record must be stored FULL
            changed = block.height + 2
        nvals.increment_proposer_priority(1)
        params = state.consensus_params
        params_changed = state.last_height_consensus_params_changed
        if resp.consensus_param_updates is not None:
            params = resp.consensus_param_updates
            params_changed = block.height + 1
        # INVARIANT (measured ~4% of replay host wall in per-validator
        # copies): published validator sets are immutable — every
        # in-place mutator (increment_proposer_priority,
        # update_with_change_set) runs on a fresh .copy() or a fresh
        # store load (consensus/state.py:511, store.py:380, nvals
        # above), so the previous state's sets can be ALIASED into the
        # new state instead of deep-copied; the valset-hash memo then
        # also carries over for free.
        new_state = State(
            chain_id=state.chain_id,
            initial_height=state.initial_height,
            last_block_height=block.height,
            last_block_id=block_id,
            last_block_time_ns=block.header.time_ns,
            validators=state.next_validators,
            next_validators=nvals,
            last_validators=state.validators,
            last_height_validators_changed=changed,
            consensus_params=params,
            last_height_consensus_params_changed=params_changed,
            last_results_hash=(
                artifacts.results_hash
                if artifacts is not None
                else results_hash(resp.tx_results)
            ),
            app_hash=resp.app_hash,
        )
        self.store.save(new_state)
        return new_state

    def _fire_events(self, block, block_id, resp, artifacts=None) -> None:
        if self.event_bus is None:
            return
        new_block_data = {
            "block": block,
            "block_id": block_id,
            "result_events": resp.events,
        }
        if artifacts is not None:
            # thread the once-flattened/encoded forms so the indexer
            # and fan-out never re-walk the attributes (optional keys:
            # events published from replay or tests simply lack them
            # and every consumer falls back to flattening itself)
            new_block_data["events_flat"] = artifacts.block_events_flat
            new_block_data["events_enc"] = artifacts.block_events_enc
        self.event_bus.publish_type(
            ev.EVENT_NEW_BLOCK, new_block_data, height=block.height
        )
        self.event_bus.publish_type(
            ev.EVENT_NEW_BLOCK_HEADER, block.header, height=block.height
        )
        for i, tx in enumerate(block.data.txs):
            data = {
                "height": block.height,
                "index": i,
                "tx": tx,
                "result": resp.tx_results[i],
            }
            if artifacts is not None:
                data["tx_hash"] = artifacts.tx_hashes[i]
                data["events_flat"] = artifacts.tx_events_flat[i]
                data["events_enc"] = artifacts.tx_events_enc[i]
                h = artifacts.tx_hashes[i].hex()
            else:
                h = hashlib.sha256(tx).hexdigest()  # bftlint: disable=ASY123 — no-artifacts fallback (replay/tests); the finalize path reuses artifacts.tx_hashes
            self.event_bus.publish_type(ev.EVENT_TX, data, hash=h)
        if resp.validator_updates:
            self.event_bus.publish_type(
                ev.EVENT_VALIDATOR_SET_UPDATES, resp.validator_updates
            )
