"""State snapshot + consensus params (reference state/state.go, types/params.go)."""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..crypto import merkle
from ..types.block import BlockID, Header
from ..types.validator_set import ValidatorSet
from ..utils import proto

BLOCK_VERSION = 11


@dataclass
class BlockParams:
    max_bytes: int = 4 * 1024 * 1024  # 4MB east of reference's 21MB cap
    max_gas: int = -1


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100_000
    max_age_duration_ns: int = 48 * 3600 * 10**9
    max_bytes: int = 1024 * 1024


@dataclass
class ValidatorParams:
    pub_key_types: List[str] = field(default_factory=lambda: ["ed25519"])


@dataclass
class ABCIParams:
    vote_extensions_enable_height: int = 0


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    abci: ABCIParams = field(default_factory=ABCIParams)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices([self.encode()])

    def to_dict(self) -> dict:
        """Genesis-JSON form (reference types/params.go in genesis)."""
        return {
            "block": {
                "max_bytes": self.block.max_bytes,
                "max_gas": self.block.max_gas,
            },
            "evidence": {
                "max_age_num_blocks": self.evidence.max_age_num_blocks,
                "max_age_duration_ns": self.evidence.max_age_duration_ns,
                "max_bytes": self.evidence.max_bytes,
            },
            "validator": {
                "pub_key_types": list(self.validator.pub_key_types)
            },
            "abci": {
                "vote_extensions_enable_height": (
                    self.abci.vote_extensions_enable_height
                )
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ConsensusParams":
        p = cls()
        b = d.get("block", {})
        p.block.max_bytes = int(b.get("max_bytes", p.block.max_bytes))
        p.block.max_gas = int(b.get("max_gas", p.block.max_gas))
        e = d.get("evidence", {})
        p.evidence.max_age_num_blocks = int(
            e.get("max_age_num_blocks", p.evidence.max_age_num_blocks)
        )
        p.evidence.max_age_duration_ns = int(
            e.get("max_age_duration_ns", p.evidence.max_age_duration_ns)
        )
        p.evidence.max_bytes = int(
            e.get("max_bytes", p.evidence.max_bytes)
        )
        v = d.get("validator", {})
        p.validator.pub_key_types = list(
            v.get("pub_key_types", p.validator.pub_key_types)
        )
        a = d.get("abci", {})
        p.abci.vote_extensions_enable_height = int(
            a.get(
                "vote_extensions_enable_height",
                p.abci.vote_extensions_enable_height,
            )
        )
        return p

    def vote_extensions_enabled(self, height: int) -> bool:
        h = self.abci.vote_extensions_enable_height
        return h > 0 and height >= h

    def encode(self) -> bytes:
        b = proto.field_varint(1, self.block.max_bytes) + proto.field_sfixed64(
            2, self.block.max_gas
        )
        e = (
            proto.field_varint(1, self.evidence.max_age_num_blocks)
            + proto.field_varint(2, self.evidence.max_age_duration_ns)
            + proto.field_varint(3, self.evidence.max_bytes)
        )
        v = b"".join(
            proto.field_string(1, t) for t in self.validator.pub_key_types
        )
        a = proto.field_varint(1, self.abci.vote_extensions_enable_height)
        return (
            proto.field_message(1, b)
            + proto.field_message(2, e)
            + proto.field_message(3, v)
            + proto.field_message(4, a)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "ConsensusParams":
        m = proto.parse(raw)
        bm = proto.parse(proto.get1(m, 1, b""))
        em = proto.parse(proto.get1(m, 2, b""))
        vm = proto.parse(proto.get1(m, 3, b""))
        am = proto.parse(proto.get1(m, 4, b""))
        return cls(
            block=BlockParams(
                max_bytes=proto.get1(bm, 1, 4 * 1024 * 1024),
                max_gas=proto.get1(bm, 2, -1),
            ),
            evidence=EvidenceParams(
                max_age_num_blocks=proto.get1(em, 1, 100_000),
                max_age_duration_ns=proto.get1(em, 2, 48 * 3600 * 10**9),
                max_bytes=proto.get1(em, 3, 1024 * 1024),
            ),
            validator=ValidatorParams(
                pub_key_types=[x.decode() for x in vm.get(1, [])] or ["ed25519"]
            ),
            abci=ABCIParams(
                vote_extensions_enable_height=proto.get1(am, 1, 0)
            ),
        )


@dataclass
class State:
    """Everything needed to validate + execute the next block
    (reference state/state.go:38-80)."""

    chain_id: str = ""
    initial_height: int = 1
    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time_ns: int = 0
    validators: Optional[ValidatorSet] = None
    next_validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0
    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def is_empty(self) -> bool:
        return self.validators is None

    def copy(self) -> "State":
        return replace(
            self,
            validators=self.validators.copy() if self.validators else None,
            next_validators=(
                self.next_validators.copy() if self.next_validators else None
            ),
            last_validators=(
                self.last_validators.copy() if self.last_validators else None
            ),
        )

    def make_header_template(
        self, height: int, time_ns: int, proposer_address: bytes
    ) -> Header:
        return Header(
            version_block=BLOCK_VERSION,
            chain_id=self.chain_id,
            height=height,
            time_ns=time_ns,
            last_block_id=self.last_block_id,
            validators_hash=self.validators.hash(),
            next_validators_hash=self.next_validators.hash(),
            consensus_hash=self.consensus_params.hash(),
            app_hash=self.app_hash,
            last_results_hash=self.last_results_hash,
            proposer_address=proposer_address,
        )
