"""State rollback (reference state/rollback.go): rewind the state one
height so the block at the current tip can be re-processed — used
after an app-hash mismatch or a faulty upgrade.

Rolls state from height H back to H-1 using the stored historical
validator sets / consensus params, and (hard mode) deletes block H
from the block store as well."""

from __future__ import annotations

import dataclasses

from ..utils import codec


class RollbackError(Exception):
    pass


def rollback_state(state_store, block_store, remove_block: bool = False):
    """Returns the rolled-back State. The reference requires the block
    store to be one ahead of (or equal to) the state store."""
    state = state_store.load()
    if state is None:
        raise RollbackError("no state found to roll back")
    rollback_height = state.last_block_height  # height to undo
    if rollback_height <= 0:
        raise RollbackError("canot rollback genesis state")
    prev_height = rollback_height - 1

    rolled_block = block_store.load_block_meta(rollback_height)
    if rolled_block is None:
        raise RollbackError(f"block at height {rollback_height} not found")
    prev_block = (
        block_store.load_block_meta(prev_height) if prev_height > 0 else None
    )

    # historical valsets: validators for H were stored when H-1 saved
    vals = state_store.load_validators(rollback_height)
    next_vals = state_store.load_validators(rollback_height + 1)
    last_vals = (
        state_store.load_validators(prev_height) if prev_height > 0 else None
    )
    if vals is None or next_vals is None:
        raise RollbackError("historical validator sets unavailable")
    params = state_store.load_consensus_params(rollback_height) or (
        state.consensus_params
    )

    # The rolled-back height may have carried the valset/params change
    # the invalid state points at; clamp the change markers so they
    # never reference a height ABOVE what the rolled-back state can
    # re-derive (reference rollback.go:69-76) — an unclamped forward
    # pointer would corrupt the S:vi record history on the next save.
    val_changed = min(
        state.last_height_validators_changed, rollback_height + 1
    )
    params_changed = min(
        state.last_height_consensus_params_changed, rollback_height
    )

    new_state = dataclasses.replace(
        state,
        last_block_height=prev_height,
        last_block_id=rolled_block.header.last_block_id,
        last_block_time_ns=(
            prev_block.header.time_ns
            if prev_block is not None
            else state.last_block_time_ns
        ),
        validators=vals,
        next_validators=next_vals,
        last_validators=last_vals,
        last_height_validators_changed=val_changed,
        consensus_params=params,
        last_height_consensus_params_changed=params_changed,
        app_hash=rolled_block.header.app_hash,
        last_results_hash=rolled_block.header.last_results_hash,
    )
    state_store.save(new_state)
    if remove_block:
        block_store.delete_latest_block()
    return new_state
