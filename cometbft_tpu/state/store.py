"""State store: per-height state, validators, params, ABCI responses
(reference state/store.go).

Key layout:
  S:state            -> latest State (slim: valset MEMBERSHIP by
                        reference, exact proposer priorities inline)
  S:vi:<height>      -> ValidatorsInfo for height: the full set when it
                        changed at <height> (or at a checkpoint), else
                        a pointer {last_height_changed}
  S:vals:<height>    -> LEGACY full ValidatorSet records (read-only
                        fallback for stores written before the pointer
                        scheme)
  S:params:<height>  -> ConsensusParams active at height (only when changed)
  S:abci:<height>    -> FinalizeBlockResponse (tx results etc.)

The pointer scheme is the reference's ValidatorsInfo /
LastHeightChanged design (state/store.go:185-251,590-640): the full
validator set is written only when it changes or at checkpoint heights;
intermediate heights store a pointer, and loads reconstruct proposer
priorities via IncrementProposerPriority(height - last_stored). This
removed the replay pipeline's dominant cost — four full 150-validator
encodings per height (VERDICT r2 missing #3). Unlike the reference's
100_000, the checkpoint interval is 1_000: reconstruction costs one
Python-side increment per height of gap, so the bound keeps historical
loads O(1000) instead of O(100k).

Exactness contract: reconstructed priorities are EXACT only when the
gap evolution applied increment(1) per height with no rescale — one
increment(k) call can diverge from k increment(1) calls once priority
spread triggers rescaling (the reference accepts the same
approximation, and ValidatorSet.hash() excludes priorities, so commit
verification and hash checks are unaffected). The LIVE state's
priorities therefore never round-trip through reconstruction: S:state
carries the three priority vectors + proposer indices inline and load()
overlays them on the membership records.
"""

from __future__ import annotations

from typing import Optional

from ..types.validator_set import ValidatorSet
from ..utils import codec, kv, proto
from .state_types import ConsensusParams, State

# full-set checkpoint cadence for unchanged valsets (see module doc)
VALSET_CHECKPOINT_INTERVAL = 1_000


def _h(prefix: bytes, height: int) -> bytes:
    return prefix + height.to_bytes(8, "big")


def _encode_prio_vector(vs: ValidatorSet) -> bytes:
    """Packed exact priorities + proposer index for one valset: count,
    then one (possibly negative -> 10-byte) varint per validator in
    stored order, then proposer_index+1 (0 = no proposer). Three of
    these encode per replayed height (the slim state blob), so the
    varint loop takes the native bulk encoder when available."""
    prop_idx = 0
    if vs.proposer is not None:
        prop_idx = vs._by_address.get(vs.proposer.address, -1) + 1
    nums = [len(vs.validators)]
    nums.extend(v.proposer_priority for v in vs.validators)
    nums.append(prop_idx)
    from ..utils import wirecodec

    nat = wirecodec.module()
    if nat is not None:
        try:
            return nat.varints(nums)
        except Exception:  # pragma: no cover - >64-bit priorities
            pass
    out = bytearray()
    for x in nums:
        out += proto.varint(x)
    return bytes(out)


def _apply_prio_vector(vs: ValidatorSet, b: bytes) -> ValidatorSet:
    n, pos = proto.read_varint(b, 0)
    if n != len(vs.validators):
        raise ValueError(
            f"priority vector length {n} != valset size {len(vs.validators)}"
        )
    for v in vs.validators:
        v.proposer_priority, pos = proto.read_varint(b, pos)
    prop_idx, pos = proto.read_varint(b, pos)
    vs.proposer = vs.validators[prop_idx - 1] if prop_idx else None
    return vs


def encode_state(s: State, embed_valsets: bool = True) -> bytes:
    """State blob. ``embed_valsets=True`` (wire/tool form) embeds the
    full validator sets; the store's slim form (False) writes only the
    exact priority vectors (fields 14-16) and reconstructs membership
    from the S:vi records on load."""
    out = proto.field_string(1, s.chain_id)
    out += proto.field_varint(2, s.initial_height)
    out += proto.field_varint(3, s.last_block_height)
    out += proto.field_message(4, s.last_block_id.encode())
    out += proto.field_varint(5, s.last_block_time_ns)
    if embed_valsets:
        if s.validators:
            out += proto.field_message(
                6, codec.encode_validator_set(s.validators)
            )
        if s.next_validators:
            out += proto.field_message(
                7, codec.encode_validator_set(s.next_validators)
            )
        if s.last_validators and s.last_validators.size() > 0:
            out += proto.field_message(
                8, codec.encode_validator_set(s.last_validators)
            )
    out += proto.field_varint(9, s.last_height_validators_changed)
    out += proto.field_message(10, s.consensus_params.encode())
    out += proto.field_varint(11, s.last_height_consensus_params_changed)
    out += proto.field_bytes(12, s.last_results_hash)
    out += proto.field_bytes(13, s.app_hash)
    if not embed_valsets:
        if s.validators:
            out += proto.field_bytes(14, _encode_prio_vector(s.validators))
        if s.next_validators:
            out += proto.field_bytes(
                15, _encode_prio_vector(s.next_validators)
            )
        if s.last_validators and s.last_validators.size() > 0:
            out += proto.field_bytes(
                16, _encode_prio_vector(s.last_validators)
            )
    return out


def decode_state(b: bytes) -> State:
    """Decode a state blob. For the slim form the valset fields come
    back None and the packed priority vectors are stashed on the State
    as ``_prio_vectors`` for Store.load() to overlay."""
    m = proto.parse(b)

    def vs(f):
        raw = proto.get1(m, f)
        return codec.decode_validator_set(raw) if raw else None

    st = State(
        chain_id=proto.get1(m, 1, b"").decode(),
        initial_height=proto.get1(m, 2, 1),
        last_block_height=proto.get1(m, 3, 0),
        last_block_id=codec.decode_block_id(proto.get1(m, 4, b"")),
        last_block_time_ns=proto.get1(m, 5, 0),
        validators=vs(6),
        next_validators=vs(7),
        last_validators=vs(8) or ValidatorSet.__new__(ValidatorSet),
        last_height_validators_changed=proto.get1(m, 9, 0),
        consensus_params=ConsensusParams.decode(proto.get1(m, 10, b"")),
        last_height_consensus_params_changed=proto.get1(m, 11, 0),
        last_results_hash=proto.get1(m, 12, b""),
        app_hash=proto.get1(m, 13, b""),
    )
    if st.validators is None:
        st._prio_vectors = (
            proto.get1(m, 14),
            proto.get1(m, 15),
            proto.get1(m, 16),
        )
    return st


# --- ValidatorsInfo records (reference state/store.go:185-251) ---------


def _encode_validators_info(
    vs: Optional[ValidatorSet], last_height_changed: int
) -> bytes:
    out = b""
    if vs is not None:
        out += proto.field_message(1, codec.encode_validator_set(vs))
    out += proto.field_varint(2, last_height_changed)
    return out


def _decode_validators_info(b: bytes):
    m = proto.parse(b)
    raw = proto.get1(m, 1)
    vs = codec.decode_validator_set(raw) if raw else None
    return vs, proto.get1(m, 2, 0)


def _last_stored_height_for(height: int, last_height_changed: int) -> int:
    checkpoint = height - height % VALSET_CHECKPOINT_INTERVAL
    return max(checkpoint, last_height_changed)


class Store:
    def __init__(self, db: kv.KV):
        self.db = db
        # highest height save() wrote in THIS instance: contiguous
        # successor saves skip the backfill/anchor existence probes
        # (their records were written by the previous save)
        self._last_saved_height: Optional[int] = None

    def load(self) -> Optional[State]:
        b = self.db.get(b"S:state")
        if b is None:
            return None
        st = decode_state(b)
        if st.validators is None and hasattr(st, "_prio_vectors"):
            # slim blob: membership from the S:vi records, EXACT
            # priorities + proposer from the inline vectors
            pv, pnv, plv = st._prio_vectors
            h = st.last_block_height
            st.validators = self.load_validators(
                h + 1, membership_only=bool(pv)
            )
            st.next_validators = self.load_validators(
                h + 2, membership_only=bool(pnv)
            )
            st.last_validators = (
                self.load_validators(h, membership_only=bool(plv))
                if h > 0
                else None
            )
            if st.validators is None or st.next_validators is None:
                raise ValueError(
                    "state blob references missing validator records "
                    f"at heights {h + 1}/{h + 2}"
                )
            if pv:
                _apply_prio_vector(st.validators, pv)
            if pnv:
                _apply_prio_vector(st.next_validators, pnv)
            if plv and st.last_validators is not None:
                _apply_prio_vector(st.last_validators, plv)
            del st._prio_vectors
        if st.last_validators is not None and not hasattr(
            st.last_validators, "validators"
        ):
            st.last_validators = None
        return st

    def save(self, state: State) -> None:
        next_height = state.last_block_height + 1
        contiguous = (
            self._last_saved_height is not None
            and state.last_block_height == self._last_saved_height + 1
        )
        sets = []
        if next_height == state.initial_height:
            # genesis: record both current and next valsets (both are
            # change points: the set "changed into existence")
            sets.append(
                (
                    _h(b"S:vi:", next_height),
                    _encode_validators_info(state.validators, next_height),
                )
            )
        elif not contiguous:
            # out-of-band saves (a state not evolved height-by-height
            # through this store — tests, tools, migrations, a fresh
            # Store instance) may lack the records earlier saves would
            # have written; backfill them full so load() can always
            # reconstruct. Contiguous successor saves skip the probes:
            # the previous save wrote these records (replay hot path).
            for hh, vs in (
                (next_height, state.validators),
                (state.last_block_height, state.last_validators),
            ):
                if (
                    vs is not None
                    and getattr(vs, "validators", None)
                    and self.db.get(_h(b"S:vi:", hh)) is None
                    and self.db.get(_h(b"S:vals:", hh)) is None
                ):
                    sets.append(
                        (
                            _h(b"S:vi:", hh),
                            _encode_validators_info(vs, hh),
                        )
                    )
        k = next_height + 1
        changed = state.last_height_validators_changed
        full = (
            k == changed
            or k % VALSET_CHECKPOINT_INTERVAL == 0
            or k <= state.initial_height + 1
            # a change marker ABOVE this record (possible only if a
            # caller skipped the rollback clamp, rollback.py) must
            # never become a forward pointer
            or changed > k
        )
        if not full and not contiguous:
            # never write a dangling pointer: the referenced full
            # record must already exist (it can be absent after an
            # out-of-band save — e.g. a state constructed directly by
            # tests/tools rather than evolved from genesis)
            k0 = _last_stored_height_for(k, changed)
            full = (
                self.db.get(_h(b"S:vi:", k0)) is None
                and self.db.get(_h(b"S:vals:", k0)) is None
            )
        sets.append(
            (
                _h(b"S:vi:", k),
                _encode_validators_info(
                    state.next_validators if full else None, changed
                ),
            )
        )
        sets.append((b"S:state", encode_state(state, embed_valsets=False)))
        sets.append(
            (_h(b"S:params:", next_height), state.consensus_params.encode())
        )
        self.db.write_batch(sets)
        self._last_saved_height = state.last_block_height

    def bootstrap(self, state: State) -> None:
        """Save a state obtained out-of-band (statesync), with history
        gaps (reference state/store.go Bootstrap): every record is a
        full set — there is no contiguous history to point into."""
        h = state.last_block_height
        sets = [(b"S:state", encode_state(state, embed_valsets=False))]
        if state.last_validators is not None and getattr(
            state.last_validators, "validators", None
        ):
            sets.append(
                (
                    _h(b"S:vi:", h),
                    _encode_validators_info(state.last_validators, h),
                )
            )
        sets.append(
            (
                _h(b"S:vi:", h + 1),
                _encode_validators_info(
                    state.validators, state.last_height_validators_changed
                ),
            )
        )
        sets.append(
            (
                _h(b"S:vi:", h + 2),
                _encode_validators_info(
                    state.next_validators,
                    state.last_height_validators_changed,
                ),
            )
        )
        sets.append((_h(b"S:params:", h + 1), state.consensus_params.encode()))
        self.db.write_batch(sets)

    def load_validators(
        self, height: int, membership_only: bool = False
    ) -> Optional[ValidatorSet]:
        """Valset for ``height``; pointer records reconstruct proposer
        priorities by incrementing from the last stored full set
        (reference state/store.go:545-588 — and the same approximation
        caveat, see module doc). ``membership_only`` skips the priority
        reconstruction (up to checkpoint-interval increment passes) for
        callers that overlay exact priorities anyway (load())."""
        b = self.db.get(_h(b"S:vi:", height))
        if b is None:
            # legacy record (pre-pointer-scheme store)
            b = self.db.get(_h(b"S:vals:", height))
            return codec.decode_validator_set(b) if b else None
        vs, changed = _decode_validators_info(b)
        if vs is not None:
            return vs
        k0 = _last_stored_height_for(height, changed)
        b0 = self.db.get(_h(b"S:vi:", k0))
        if b0 is not None:
            vs, _ = _decode_validators_info(b0)
        else:  # stored-full height predates the scheme: legacy record
            raw = self.db.get(_h(b"S:vals:", k0))
            vs = codec.decode_validator_set(raw) if raw else None
        if vs is None:
            raise ValueError(
                f"validators at height {height} point to missing full "
                f"record at {k0}"
            )
        if not membership_only:
            vs.increment_proposer_priority(height - k0)
        return vs

    def load_consensus_params(self, height: int) -> Optional[ConsensusParams]:
        b = self.db.get(_h(b"S:params:", height))
        if b is not None:
            return ConsensusParams.decode(b)
        # walk back to the last change checkpoint
        for hh in range(height, 0, -1):
            b = self.db.get(_h(b"S:params:", hh))
            if b is not None:
                return ConsensusParams.decode(b)
        return None

    def save_finalize_block_response(self, height: int, encoded: bytes) -> None:
        self.db.set(_h(b"S:abci:", height), encoded)

    def load_finalize_block_response(self, height: int) -> Optional[bytes]:
        return self.db.get(_h(b"S:abci:", height))

    def prune_states(self, retain_height: int) -> None:
        # Pointer records at heights >= retain_height may reference a
        # full record BELOW it: keep everything from that anchor up
        # (reference state/store.go:299 keeps the last checkpoint).
        # The pruning floor is the anchor of the first POINTER record
        # at or above retain_height — pointer anchors
        # max(checkpoint(h), changed) are monotone in h (checkpoint
        # grows with h; changed never decreases along a chain), so the
        # first one bounds every later anchor. Full records along the
        # way are skipped, NOT trusted as a floor: a full record is
        # not necessarily a change point (save()'s upgrade backfill
        # writes them mid-stream), so a pointer above it can still
        # anchor below it — including below retain_height, e.g. at a
        # legacy S:vals record on an upgraded store (ADVICE r3).
        keep_from = retain_height
        for k, v in self.db.iter_prefix(b"S:vi:"):
            h = int.from_bytes(k[len(b"S:vi:") :], "big")
            if h < retain_height:
                continue
            vs, changed = _decode_validators_info(v)
            if vs is None:
                keep_from = min(
                    keep_from, _last_stored_height_for(h, changed)
                )
                break
        deletes = []
        for prefix in (b"S:vi:", b"S:vals:"):
            for k, _ in self.db.iter_prefix(prefix):
                h = int.from_bytes(k[len(prefix) :], "big")
                if h < keep_from:
                    deletes.append(k)
        for k, _ in self.db.iter_prefix(b"S:abci:"):
            h = int.from_bytes(k[len(b"S:abci:") :], "big")
            if h < retain_height:
                deletes.append(k)
        if deletes:
            self.db.write_batch([], deletes)
