"""State store: per-height state, validators, params, ABCI responses
(reference state/store.go).

Key layout:
  S:state            -> latest State
  S:vals:<height>    -> ValidatorSet active AT height
  S:params:<height>  -> ConsensusParams active at height (only when changed)
  S:abci:<height>    -> FinalizeBlockResponse (tx results etc.)
"""

from __future__ import annotations

from typing import Optional

from ..types.validator_set import ValidatorSet
from ..utils import codec, kv, proto
from .state_types import ConsensusParams, State


def _h(prefix: bytes, height: int) -> bytes:
    return prefix + height.to_bytes(8, "big")


def encode_state(s: State) -> bytes:
    out = proto.field_string(1, s.chain_id)
    out += proto.field_varint(2, s.initial_height)
    out += proto.field_varint(3, s.last_block_height)
    out += proto.field_message(4, s.last_block_id.encode())
    out += proto.field_varint(5, s.last_block_time_ns)
    if s.validators:
        out += proto.field_message(6, codec.encode_validator_set(s.validators))
    if s.next_validators:
        out += proto.field_message(
            7, codec.encode_validator_set(s.next_validators)
        )
    if s.last_validators and s.last_validators.size() > 0:
        out += proto.field_message(
            8, codec.encode_validator_set(s.last_validators)
        )
    out += proto.field_varint(9, s.last_height_validators_changed)
    out += proto.field_message(10, s.consensus_params.encode())
    out += proto.field_varint(11, s.last_height_consensus_params_changed)
    out += proto.field_bytes(12, s.last_results_hash)
    out += proto.field_bytes(13, s.app_hash)
    return out


def decode_state(b: bytes) -> State:
    m = proto.parse(b)

    def vs(f):
        raw = proto.get1(m, f)
        return codec.decode_validator_set(raw) if raw else None

    return State(
        chain_id=proto.get1(m, 1, b"").decode(),
        initial_height=proto.get1(m, 2, 1),
        last_block_height=proto.get1(m, 3, 0),
        last_block_id=codec.decode_block_id(proto.get1(m, 4, b"")),
        last_block_time_ns=proto.get1(m, 5, 0),
        validators=vs(6),
        next_validators=vs(7),
        last_validators=vs(8) or ValidatorSet.__new__(ValidatorSet),
        last_height_validators_changed=proto.get1(m, 9, 0),
        consensus_params=ConsensusParams.decode(proto.get1(m, 10, b"")),
        last_height_consensus_params_changed=proto.get1(m, 11, 0),
        last_results_hash=proto.get1(m, 12, b""),
        app_hash=proto.get1(m, 13, b""),
    )


class Store:
    def __init__(self, db: kv.KV):
        self.db = db

    def load(self) -> Optional[State]:
        b = self.db.get(b"S:state")
        if b is None:
            return None
        st = decode_state(b)
        if st.last_validators is not None and not hasattr(
            st.last_validators, "validators"
        ):
            st.last_validators = None
        return st

    def save(self, state: State) -> None:
        next_height = state.last_block_height + 1
        if next_height == state.initial_height:
            # genesis: record both current and next valsets
            self.db.set(
                _h(b"S:vals:", next_height),
                codec.encode_validator_set(state.validators),
            )
        sets = [
            (b"S:state", encode_state(state)),
            (
                _h(b"S:vals:", next_height + 1),
                codec.encode_validator_set(state.next_validators),
            ),
            (
                _h(b"S:params:", next_height),
                state.consensus_params.encode(),
            ),
        ]
        self.db.write_batch(sets)

    def bootstrap(self, state: State) -> None:
        """Save a state obtained out-of-band (statesync), with history
        gaps (reference state/store.go Bootstrap)."""
        h = state.last_block_height
        sets = [(b"S:state", encode_state(state))]
        if state.last_validators is not None and getattr(
            state.last_validators, "validators", None
        ):
            sets.append(
                (
                    _h(b"S:vals:", h),
                    codec.encode_validator_set(state.last_validators),
                )
            )
        sets.append(
            (_h(b"S:vals:", h + 1), codec.encode_validator_set(state.validators))
        )
        sets.append(
            (
                _h(b"S:vals:", h + 2),
                codec.encode_validator_set(state.next_validators),
            )
        )
        sets.append((_h(b"S:params:", h + 1), state.consensus_params.encode()))
        self.db.write_batch(sets)

    def load_validators(self, height: int) -> Optional[ValidatorSet]:
        b = self.db.get(_h(b"S:vals:", height))
        return codec.decode_validator_set(b) if b else None

    def load_consensus_params(self, height: int) -> Optional[ConsensusParams]:
        b = self.db.get(_h(b"S:params:", height))
        if b is not None:
            return ConsensusParams.decode(b)
        # walk back to the last change checkpoint
        for hh in range(height, 0, -1):
            b = self.db.get(_h(b"S:params:", hh))
            if b is not None:
                return ConsensusParams.decode(b)
        return None

    def save_finalize_block_response(self, height: int, encoded: bytes) -> None:
        self.db.set(_h(b"S:abci:", height), encoded)

    def load_finalize_block_response(self, height: int) -> Optional[bytes]:
        return self.db.get(_h(b"S:abci:", height))

    def prune_states(self, retain_height: int) -> None:
        deletes = []
        for k, _ in self.db.iter_prefix(b"S:vals:"):
            h = int.from_bytes(k[len(b"S:vals:") :], "big")
            if h < retain_height:
                deletes.append(k)
        for k, _ in self.db.iter_prefix(b"S:abci:"):
            h = int.from_bytes(k[len(b"S:abci:") :], "big")
            if h < retain_height:
                deletes.append(k)
        if deletes:
            self.db.write_batch([], deletes)
