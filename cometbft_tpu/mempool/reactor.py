"""Mempool reactor: tx gossip on channel 0x30 (reference
mempool/reactor.go, channel id at mempool/mempool.go:13).

Per-peer broadcast routine mirrors the reference's clist-waiter loop
(mempool/reactor.go:217 broadcastTxRoutine): walk the mempool's tx
list in insertion order, skip txs the peer itself sent us (sender
tracking), and push everything else. Inbound txs go through the full
CheckTx path, so invalid txs never propagate."""

from __future__ import annotations

import asyncio
import traceback
from typing import Dict

from ..p2p.node_info import ChannelDescriptor
from ..p2p.reactor import Reactor
from .mempool import tx_key

MEMPOOL_CHANNEL = 0x30
GOSSIP_INTERVAL_S = 0.05


class MempoolReactor(Reactor):
    name = "mempool"

    def __init__(self, mempool, broadcast: bool = True):
        super().__init__()
        self.mempool = mempool
        self.broadcast = broadcast  # config.Mempool.Broadcast
        self._tasks: Dict[str, asyncio.Task] = {}

    def get_channels(self):
        return [
            ChannelDescriptor(MEMPOOL_CHANNEL, priority=5, max_msg_size=1 << 20)
        ]

    def add_peer(self, peer) -> None:
        if self.broadcast:
            self._tasks[peer.peer_id] = asyncio.create_task(
                self._broadcast_tx_routine(peer)
            )

    def remove_peer(self, peer, reason) -> None:
        t = self._tasks.pop(peer.peer_id, None)
        if t:
            t.cancel()

    async def stop(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()

    async def _broadcast_tx_routine(self, peer) -> None:
        cursor = 0
        use_cursor = hasattr(self.mempool, "txs_after")
        sent = set()  # fallback path only
        try:
            while True:
                if use_cursor:
                    # seq-cursor over the insertion log: O(new txs) per
                    # tick, no rescans, no re-flood
                    for seq, tx, senders in self.mempool.txs_after(cursor):
                        cursor = max(cursor, seq)
                        if peer.peer_id in senders:
                            continue  # peer gave it to us; don't echo
                        await peer.send(MEMPOOL_CHANNEL, tx)
                else:
                    for tx in self.mempool.iter_txs():
                        k = tx_key(tx)
                        if k in sent:
                            continue
                        sent.add(k)
                        await peer.send(MEMPOOL_CHANNEL, tx)
                await asyncio.sleep(GOSSIP_INTERVAL_S)
        except asyncio.CancelledError:
            raise
        except Exception:
            traceback.print_exc()

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        try:
            self.mempool.check_tx(msg, sender=peer.peer_id)
        except Exception:
            pass  # invalid txs are dropped, not fatal to the peer


class AppMempoolReactor(Reactor):
    """Fork feature: gossip for the app-side mempool (reference
    mempool/app_reactor.go). The app owns tx storage, so there is no
    pool to walk — relaying is flood-with-dedup: a tx accepted by
    InsertTx (guard-deduplicated) is forwarded to every OTHER peer
    exactly once."""

    name = "mempool"

    def __init__(self, mempool, broadcast: bool = True):
        super().__init__()
        self.mempool = mempool  # AppMempool
        self.broadcast = broadcast

    def get_channels(self):
        return [
            ChannelDescriptor(MEMPOOL_CHANNEL, priority=5, max_msg_size=1 << 20)
        ]

    def submit_local(self, tx: bytes):
        """Entry for locally-submitted txs (RPC broadcast_tx path)."""
        res = self.mempool.check_tx(tx)
        if res.is_ok() and self.broadcast and self.switch is not None:
            self.switch.broadcast(MEMPOOL_CHANNEL, tx)
        return res

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        try:
            res = self.mempool.check_tx(msg, sender=peer.peer_id)
        except Exception:
            return
        if res.is_ok() and self.broadcast and self.switch is not None:
            # forward to everyone but the sender (guard stops loops)
            for p in self.switch.peers.values():
                if p.peer_id != peer.peer_id:
                    p.try_send(MEMPOOL_CHANNEL, msg)
