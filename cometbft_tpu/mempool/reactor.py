"""Mempool reactor: tx gossip on channel 0x30 (reference
mempool/reactor.go, channel id at mempool/mempool.go:13).

Per-peer broadcast routine mirrors the reference's clist-waiter loop
(mempool/reactor.go:217 broadcastTxRoutine): walk the mempool's tx
list in insertion order, skip txs the peer itself sent us (sender
tracking), and push everything else — coalesced into batch frames
(mempool/codec.py) up to ``gossip_batch_bytes`` per message. Inbound
txs decode and land on the micro-batching ingest queue
(mempool/ingest.py), so ``receive`` never blocks the event loop on an
ABCI call; the full CheckTx path still gates propagation, so invalid
txs never re-gossip."""

from __future__ import annotations

import asyncio
import traceback
from collections import OrderedDict
from typing import Dict, List

from ..p2p import tracewire
from ..p2p.node_info import ChannelDescriptor
from ..p2p.reactor import Reactor
from ..utils.tasks import spawn
from . import codec
from .ingest import IngestQueue
from .mempool import tx_key

MEMPOOL_CHANNEL = 0x30
GOSSIP_INTERVAL_S = 0.05
# txs the legacy fallback path remembers per peer (no txs_after
# cursor): bounded so a long-lived peer can't grow the set forever
SENT_CACHE_SIZE = 65536
# hard frame cap = the channel descriptor's max_msg_size: a frame
# that crosses it kills the whole peer connection on the receiver
MAX_FRAME_BYTES = 1 << 20


def _frame_overhead(n_txs: int) -> int:
    """Worst-case batch framing bytes: magic + count varint + one
    length varint per tx (5 bytes covers lengths up to 2^35)."""
    return len(codec.MAGIC) + 5 + 5 * n_txs


class MempoolReactor(Reactor):
    name = "mempool"

    def __init__(
        self,
        mempool,
        broadcast: bool = True,
        batch_max_txs: int = 256,
        batch_flush_ms: float = 2.0,
        gossip_batch_bytes: int = 64 * 1024,
    ):
        super().__init__()
        self.mempool = mempool
        self.broadcast = broadcast  # config.Mempool.Broadcast
        self.gossip_batch_bytes = max(1, gossip_batch_bytes)
        self.batch_max_txs = max(1, batch_max_txs)
        self.ingest = IngestQueue(
            mempool,
            batch_max_txs=batch_max_txs,
            batch_flush_ms=batch_flush_ms,
        )
        self._tasks: Dict[str, asyncio.Task] = {}

    def get_channels(self):
        return [
            ChannelDescriptor(MEMPOOL_CHANNEL, priority=5, max_msg_size=1 << 20)
        ]

    async def start(self) -> None:
        self.ingest.start()

    def add_peer(self, peer) -> None:
        if self.broadcast:
            self._tasks[peer.peer_id] = asyncio.create_task(
                self._broadcast_tx_routine(peer)
            )

    def remove_peer(self, peer, reason) -> None:
        t = self._tasks.pop(peer.peer_id, None)
        if t:
            t.cancel()

    async def stop(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()
        # bounded (ASY110): ingest.stop is internally bounded; belt
        # over braces so a hung drain can't wedge the switch stop
        try:
            await asyncio.wait_for(self.ingest.stop(), 10.0)
        except asyncio.TimeoutError:
            pass

    async def _send_txs(self, peer, txs: List[bytes]) -> None:
        msg = codec.encode_txs(txs)
        if len(txs) == 1 and len(msg) > MAX_FRAME_BYTES:
            # a tx so large that the batch-of-one framing crosses the
            # channel cap: send the pre-batching wire form (raw tx,
            # <= max_tx_bytes <= channel cap); the receiver's decode
            # falls back to single-tx on the inevitable parse failure.
            # encode_plain still escapes a stamp-magic-prefixed tx so
            # the receiver's always-on peel cannot mutate it (raw only
            # when even the 3-byte escape would cross the cap)
            msg = tracewire.encode_plain(txs[0], MAX_FRAME_BYTES)
        elif self.switch is not None:
            # cross-node tracing: gossip batches carry the trace
            # stamp OUTSIDE the tx framing (stamp_msg skips payloads
            # too close to the channel cap)
            msg = self.switch.stamp_msg(
                MEMPOOL_CHANNEL, msg, "txs", peer=peer.peer_id
            )
        await peer.send(MEMPOOL_CHANNEL, msg)

    async def _broadcast_tx_routine(self, peer) -> None:
        cursor = 0
        use_cursor = hasattr(self.mempool, "txs_after")
        # fallback path only: bounded LRU of tx keys already pushed
        sent: "OrderedDict[bytes, None]" = OrderedDict()
        try:
            while True:
                pending: List[bytes] = []
                pending_bytes = 0

                async def flush():
                    nonlocal pending, pending_bytes
                    if pending:
                        await self._send_txs(peer, pending)
                        pending, pending_bytes = [], 0

                async def push(tx):
                    nonlocal pending_bytes
                    # flush BEFORE appending when this tx would push
                    # the frame past the channel cap (gossip_batch_
                    # bytes is a soft target; MAX_FRAME_BYTES kills
                    # the peer connection if crossed)
                    if pending and (
                        pending_bytes
                        + len(tx)
                        + _frame_overhead(len(pending) + 1)
                        > MAX_FRAME_BYTES
                    ):
                        await flush()
                    pending.append(tx)
                    pending_bytes += len(tx)
                    if (
                        pending_bytes >= self.gossip_batch_bytes
                        or len(pending) >= self.batch_max_txs
                    ):
                        await flush()

                if use_cursor:
                    # seq-cursor over the insertion log: O(new txs) per
                    # tick, no rescans, no re-flood
                    for seq, tx, senders in self.mempool.txs_after(cursor):
                        cursor = max(cursor, seq)
                        if peer.peer_id in senders:
                            continue  # peer gave it to us; don't echo
                        await push(tx)
                else:
                    for tx in self.mempool.iter_txs():
                        k = tx_key(tx)
                        if k in sent:
                            sent.move_to_end(k)
                            continue
                        sent[k] = None
                        while len(sent) > SENT_CACHE_SIZE:
                            sent.popitem(last=False)
                        await push(tx)
                await flush()
                await asyncio.sleep(GOSSIP_INTERVAL_S)
        except asyncio.CancelledError:
            raise
        except Exception:
            traceback.print_exc()

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        try:
            txs = codec.decode_txs(msg)
        except Exception:
            return  # malformed frame: drop, not fatal to the peer
        if self.ingest.running:
            for tx in txs:
                # a full queue drops the tx (counted): gossip is
                # best-effort, and shedding beats an unbounded queue
                self.ingest.submit_nowait(tx, sender=peer.peer_id)
        else:
            # ingest plane not started (reactor used standalone in
            # tests / unwired embedders): degrade to the direct path
            for tx in txs:
                self._check_tx_direct(tx, peer.peer_id)

    def _check_tx_direct(self, tx: bytes, sender: str) -> None:
        """Legacy direct CheckTx (blocks the caller); only the
        degraded path above uses it — live nodes go through the
        ingest queue so ``receive`` stays non-blocking."""
        try:
            self.mempool.check_tx(tx, sender=sender)
        except Exception:
            pass  # invalid txs are dropped, not fatal to the peer


class AppMempoolReactor(Reactor):
    """Fork feature: gossip for the app-side mempool (reference
    mempool/app_reactor.go). The app owns tx storage, so there is no
    pool to walk — relaying is flood-with-dedup: a tx accepted by
    InsertTx (guard-deduplicated) is forwarded to every OTHER peer
    exactly once."""

    name = "mempool"

    def __init__(self, mempool, broadcast: bool = True):
        super().__init__()
        self.mempool = mempool  # AppMempool
        self.broadcast = broadcast

    def get_channels(self):
        return [
            ChannelDescriptor(MEMPOOL_CHANNEL, priority=5, max_msg_size=1 << 20)
        ]

    def submit_local(self, tx: bytes):
        """Entry for locally-submitted txs (RPC broadcast_tx path)."""
        res = self.mempool.check_tx(tx)
        if res.is_ok() and self.broadcast and self.switch is not None:
            self.switch.broadcast(MEMPOOL_CHANNEL, tx, tkind="txs")
        return res

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        # InsertTx is a sync ABCI call: run it off-loop, forward on ok
        # (guard stops loops) — receive itself never blocks (ASY108)
        spawn(
            self._receive_async(peer.peer_id, msg),
            name="app-mempool-receive",
        )

    async def _receive_async(self, sender: str, msg: bytes) -> None:
        try:
            res = await asyncio.to_thread(
                self.mempool.check_tx, msg, sender
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        if res.is_ok() and self.broadcast and self.switch is not None:
            # forward to everyone but the sender (guard stops loops);
            # encode once — stamp_msg escapes a magic-prefixed raw tx
            # (attacker-shaped bytes) so receivers never mutate it
            wire = self.switch.stamp_msg(MEMPOOL_CHANNEL, msg, "txs")
            for p in self.switch.peers.values():
                if p.peer_id != sender:
                    p.try_send(MEMPOOL_CHANNEL, wire)
