"""Micro-batching ingest queue in front of the mempool.

Every tx source that used to call ``mempool.check_tx`` synchronously
on the event loop (p2p ``MempoolReactor.receive``, the RPC
broadcast_tx_* routes) enqueues here instead. A single drainer task
coalesces whatever is pending — up to ``batch_max_txs`` txs or
``batch_flush_ms`` after the first one arrived — and runs ONE
``mempool.check_tx_batch`` off-loop (``asyncio.to_thread``), so:

- the event loop never blocks on an ABCI round-trip (bftlint ASY108);
- per-tx costs (client lock, cache lock, pool lock, key hashing) are
  paid once per batch (docs/PERF.md "Mempool ingest plane").

Two entries: ``submit_nowait`` (fire-and-forget, p2p inbound —
bounded queue, drops + counts under overload) and ``await submit``
(RPC paths that must return the CheckTx verdict).
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

from ..abci import types as abci
from ..obs.queues import InstrumentedQueue
from ..trace import NOOP as TRACE_NOOP
from ..utils.log import get_logger

_log = get_logger("mempool.ingest")

_Item = Tuple[bytes, str, Optional["asyncio.Future"]]


class IngestQueue:
    tracer = TRACE_NOOP

    def __init__(
        self,
        mempool,
        batch_max_txs: int = 256,
        batch_flush_ms: float = 2.0,
        max_queue: int = 10_000,
    ):
        self.mempool = mempool
        self.batch_max_txs = max(1, batch_max_txs)
        self.flush_s = max(0.0, batch_flush_ms) / 1000.0
        self.max_queue = max_queue
        self._q: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        # counters (metrics surface + tests)
        self.submitted = 0
        self.dropped = 0
        self.batches = 0
        self.checked = 0

    # --- lifecycle ----------------------------------------------------

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    def start(self) -> None:
        if self.running:
            return
        from ..utils.tasks import spawn

        self._q = InstrumentedQueue(self.max_queue, name="mempool.ingest")
        self._task = spawn(self._drain(), name="mempool-ingest")

    async def stop(self) -> None:
        t, self._task = self._task, None
        if t is not None:
            t.cancel()
            try:
                # bounded (ASY110): a drain batch stuck in the ABCI
                # executor must not wedge the reactor stop
                await asyncio.wait_for(t, 5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass
        q, self._q = self._q, None
        if q is not None:
            while not q.empty():
                self._resolve(
                    q.get_nowait(),
                    abci.ResponseCheckTx(code=1, log="ingest stopped"),
                )

    def queue_stats(self):
        """Backpressure telemetry (obs/queues.py registry entry);
        ``dropped`` is the plane-lifetime shed count — the live queue
        is rebuilt on every start()."""
        q = self._q
        if q is None:
            return None
        s = q.stats()
        s["dropped"] = self.dropped
        return s

    # --- entries ------------------------------------------------------

    def submit_nowait(self, tx: bytes, sender: str = "") -> bool:
        """Fire-and-forget enqueue (p2p inbound). False = not running
        or queue full (overload backpressure: the tx is dropped, the
        peer will re-gossip it)."""
        q = self._q
        if q is None:
            return False
        try:
            q.put_nowait((tx, sender, None))
        except asyncio.QueueFull:
            self.dropped += 1
            q.count_drop()  # unified shed counter (obs/queues.py)
            return False
        self.submitted += 1
        return True

    async def submit(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        """Enqueue and await the CheckTx verdict (RPC broadcast)."""
        q = self._q
        if q is None:
            raise RuntimeError("ingest queue is not running")
        fut = asyncio.get_running_loop().create_future()
        await q.put((tx, sender, fut))
        self.submitted += 1
        return await fut

    # --- drainer ------------------------------------------------------

    @staticmethod
    def _resolve(item: _Item, res: abci.ResponseCheckTx) -> None:
        fut = item[2]
        if fut is not None and not fut.done():
            fut.set_result(res)

    async def _collect(self, q: "asyncio.Queue") -> List[_Item]:
        """One coalescing window: block for the first item, then keep
        taking until the batch is full or flush_ms elapsed since the
        first arrival."""
        batch: List[_Item] = [await q.get()]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.flush_s
        try:
            while len(batch) < self.batch_max_txs:
                if not q.empty():
                    batch.append(q.get_nowait())
                    continue
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(q.get(), remaining)
                    )
                except asyncio.TimeoutError:
                    break
        except asyncio.CancelledError:
            # stop() mid-window: items already popped off the queue
            # would otherwise leave their RPC callers awaiting forever
            for item in batch:
                self._resolve(
                    item,
                    abci.ResponseCheckTx(code=1, log="ingest stopped"),
                )
            raise
        return batch

    async def _drain(self) -> None:
        q = self._q
        while True:
            batch = await self._collect(q)
            txs = [b[0] for b in batch]
            senders = [b[1] for b in batch]
            try:
                results = await asyncio.to_thread(
                    self.mempool.check_tx_batch, txs, senders
                )
            except asyncio.CancelledError:
                for item in batch:
                    self._resolve(
                        item,
                        abci.ResponseCheckTx(code=1, log="ingest stopped"),
                    )
                raise
            except Exception as e:
                # an app/proxy blow-up fails THIS batch, not the plane
                _log.error("ingest batch failed", err=repr(e))
                for item in batch:
                    self._resolve(
                        item,
                        abci.ResponseCheckTx(
                            code=1, log=f"ingest failed: {e!r}"
                        ),
                    )
                continue
            self.batches += 1
            self.checked += len(batch)
            for item, res in zip(batch, results):
                self._resolve(item, res)
