"""Mempools: clist-equivalent, nop (ADR-111), app-side (fork feature).

CListMempool parity (reference mempool/clist_mempool.go): CheckTx
through the mempool ABCI connection, LRU tx cache, ordered pool, reap
by max bytes/gas, post-commit update with recheck, TxsAvailable
notification. The reference's concurrent linked list becomes an
insertion-ordered dict under one lock — the Python runtime serializes
reactor callbacks anyway; gossip iterates over snapshots.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..abci import types as abci
from ..trace import NOOP as TRACE_NOOP


def tx_key(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


class TxCache:
    """LRU of recently seen tx keys (reference mempool/cache.go)."""

    def __init__(self, size: int = 10000):
        self.size = size
        self._od: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present."""
        k = tx_key(tx)
        with self._lock:
            if k in self._od:
                self._od.move_to_end(k)
                return False
            self._od[k] = None
            while len(self._od) > self.size:
                self._od.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._lock:
            self._od.pop(tx_key(tx), None)

    def has(self, tx: bytes) -> bool:
        with self._lock:
            return tx_key(tx) in self._od


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height when entering the pool
    gas_wanted: int = 0
    senders: set = field(default_factory=set)


class Mempool:
    """Interface (reference mempool/mempool.go Mempool)."""

    # tracing plane (trace/): the node build swaps in the per-node
    # tracer; class-level NOOP keeps every flavor's call sites
    # unconditional
    tracer = TRACE_NOOP

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        raise NotImplementedError

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        raise NotImplementedError

    def update(self, height, txs, results) -> None:
        raise NotImplementedError

    def lock(self):
        raise NotImplementedError

    def unlock(self):
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def txs_available(self) -> threading.Event:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def iter_txs(self) -> List[bytes]:
        raise NotImplementedError


class CListMempool(Mempool):
    def __init__(
        self,
        proxy_app,
        height: int = 0,
        cache_size: int = 10000,
        max_tx_bytes: int = 1024 * 1024,
        max_txs: int = 5000,
        recheck: bool = True,
        notify: Optional[Callable[[], None]] = None,
    ):
        self.proxy = proxy_app
        self.height = height
        self.cache = TxCache(cache_size)
        self.pool: "OrderedDict[bytes, MempoolTx]" = OrderedDict()
        # monotonic insertion log: gossip routines keep a per-peer seq
        # cursor instead of rescanning the pool (the reference's clist
        # waiter, mempool/reactor.go:217)
        self._seq = 0
        self._log: List[tuple] = []  # (seq, tx_key), insertion order
        self.max_tx_bytes = max_tx_bytes
        self.max_txs = max_txs
        self.recheck = recheck
        self._lock = threading.RLock()
        self._txs_available = threading.Event()
        self._notify = notify

    # --- ingress ------------------------------------------------------

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        tr = self.tracer
        if not tr.enabled:
            return self._check_tx(tx, sender)
        with tr.span("mempool.insert", tid="mempool", bytes=len(tx)) as sp:
            res = self._check_tx(tx, sender)
            sp.set(ok=res.is_ok())
        # unlocked len read (like update's counter): a size() here
        # would re-take the pool lock once per tx just for the stamp
        tr.counter("mempool.size", len(self.pool), tid="mempool")
        return res

    def _check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        if len(tx) > self.max_tx_bytes:
            return abci.ResponseCheckTx(code=1, log="tx too large")
        if not self.cache.push(tx):
            k = tx_key(tx)
            with self._lock:
                if k in self.pool and sender:
                    self.pool[k].senders.add(sender)
            return abci.ResponseCheckTx(code=1, log="tx already in cache")
        res = self.proxy.check_tx(abci.RequestCheckTx(tx=tx))
        if res.is_ok():
            with self._lock:
                if len(self.pool) >= self.max_txs:
                    self.cache.remove(tx)
                    return abci.ResponseCheckTx(code=1, log="mempool full")
                mt = MempoolTx(tx=tx, height=self.height, gas_wanted=res.gas_wanted)
                if sender:
                    mt.senders.add(sender)
                self.pool[tx_key(tx)] = mt
                self._seq += 1
                self._log.append((self._seq, tx_key(tx)))
                self._txs_available.set()
            if self._notify:
                self._notify()
        else:
            self.cache.remove(tx)
        return res

    # --- egress -------------------------------------------------------

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        out, total_b, total_g = [], 0, 0
        with self.tracer.span("mempool.reap", tid="mempool") as sp:
            with self._lock:
                for mt in self.pool.values():
                    nb = total_b + len(mt.tx)
                    ng = total_g + mt.gas_wanted
                    if max_bytes >= 0 and nb > max_bytes:
                        break
                    if max_gas >= 0 and ng > max_gas:
                        break
                    out.append(mt.tx)
                    total_b, total_g = nb, ng
            sp.set(txs=len(out), bytes=total_b)
        return out

    def iter_txs(self) -> List[bytes]:
        with self._lock:
            return [mt.tx for mt in self.pool.values()]

    def tx_senders(self, key: bytes):
        """Peers that gave us this tx (gossip echo suppression,
        reference mempool/reactor.go broadcastTxRoutine)."""
        with self._lock:
            mt = self.pool.get(key)
            return set(mt.senders) if mt else ()

    def txs_after(self, seq: int) -> List[tuple]:
        """(seq, tx, senders) for pooled txs inserted after `seq` —
        the per-peer gossip cursor."""
        import bisect

        with self._lock:
            i = bisect.bisect_right(self._log, seq, key=lambda e: e[0])
            out = []
            for s, k in self._log[i:]:
                mt = self.pool.get(k)
                if mt is not None:
                    out.append((s, mt.tx, set(mt.senders)))
            return out

    def size(self) -> int:
        with self._lock:
            return len(self.pool)

    # --- post-commit --------------------------------------------------

    def lock(self):
        self._lock.acquire()

    def unlock(self):
        self._lock.release()

    def update(self, height: int, txs: List[bytes], results) -> None:
        """Called with the mempool LOCKED, between FinalizeBlock and
        releasing consensus (reference clist_mempool.go:583)."""
        self.height = height
        for tx, res in zip(txs, results):
            if res.is_ok():
                self.cache.push(tx)  # keep committed txs in cache
            else:
                self.cache.remove(tx)
            self.pool.pop(tx_key(tx), None)
        if self.recheck and self.pool:
            self._recheck_txs()
        if len(self._log) > 4 * len(self.pool) + 1024:
            self._log = [e for e in self._log if e[1] in self.pool]
        if self.pool:
            self._txs_available.set()
            if self._notify:
                self._notify()
        else:
            self._txs_available.clear()
        self.tracer.counter("mempool.size", len(self.pool), tid="mempool")

    def _recheck_txs(self) -> None:
        for k in list(self.pool.keys()):
            mt = self.pool[k]
            res = self.proxy.check_tx(
                abci.RequestCheckTx(
                    tx=mt.tx, type_=abci.CHECK_TX_TYPE_RECHECK
                )
            )
            if not res.is_ok():
                del self.pool[k]
                self.cache.remove(mt.tx)

    def txs_available(self) -> threading.Event:
        return self._txs_available

    def flush(self) -> None:
        with self._lock:
            self.pool.clear()
            self._txs_available.clear()


class NopMempool(Mempool):
    """ADR-111: mempool disabled (reference mempool/nop_mempool.go)."""

    def check_tx(self, tx, sender=""):
        return abci.ResponseCheckTx(code=1, log="mempool disabled")

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return []

    def update(self, height, txs, results):
        pass

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self):
        return 0

    def txs_available(self) -> threading.Event:
        return threading.Event()  # never set

    def flush(self):
        pass

    def iter_txs(self):
        return []


class AppMempool(Mempool):
    """Fork feature: the application owns the pool; the node only relays
    InsertTx / ReapTxs (reference mempool/app_mempool.go:23-50) with a
    TTL'd dedup guard in front (internal/guard)."""

    def __init__(self, proxy_app, guard_ttl_s: float = 60.0, guard_size: int = 100_000):
        from ..utils.guard import TTLGuard

        self.proxy = proxy_app
        self.guard = TTLGuard(ttl_s=guard_ttl_s, max_size=guard_size)
        self._txs_available = threading.Event()

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        if not self.guard.check_and_set(tx_key(tx)):
            return abci.ResponseCheckTx(code=1, log="duplicate (guard)")
        ok = self.proxy.insert_tx(tx)
        if ok:
            self._txs_available.set()
        return abci.ResponseCheckTx(
            code=abci.CODE_TYPE_OK if ok else 1
        )

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return self.proxy.reap_txs(max_bytes, max_gas)

    def iter_txs(self):
        return []  # the app owns the pool; nothing to walk

    def update(self, height, txs, results):
        self._txs_available.clear()

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self):
        return -1  # unknown: app-owned

    def txs_available(self) -> threading.Event:
        return self._txs_available

    def flush(self):
        pass

    def iter_txs(self):
        return self.proxy.reap_txs(-1, -1)
