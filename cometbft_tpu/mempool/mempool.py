"""Mempools: clist-equivalent, nop (ADR-111), app-side (fork feature).

CListMempool parity (reference mempool/clist_mempool.go): CheckTx
through the mempool ABCI connection, LRU tx cache, ordered pool, reap
by max bytes/gas, post-commit update with recheck, TxsAvailable
notification. The reference's concurrent linked list becomes an
insertion-ordered dict under one lock — the Python runtime serializes
reactor callbacks anyway; gossip iterates over snapshots.

Ingest plane (docs/PERF.md "Mempool ingest plane"): beside the serial
``check_tx`` path there is a batched one — ``check_tx_batch`` hashes
every tx key in one native pass (tx_keys), prechecks against the
cache under one cache lock, issues ONE ``check_tx_batch`` ABCI call
(per-tx fallback preserved) and admits the survivors under one pool
lock. Post-commit recheck can run asynchronously (``async_recheck``):
``update()`` snapshots the pool and returns immediately; a background
executor rechecks the snapshot in one batched ABCI call, and a
generation guard drops stale verdicts for txs committed/evicted since
the snapshot. While a recheck is in flight its txs are masked from
``reap_max_bytes_max_gas`` so a proposer never includes a tx whose
post-commit validity is still unknown (the reference's
notifyTxsAvailable-after-recheck discipline).
"""

from __future__ import annotations

import hashlib
import threading
import traceback
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis.runtime import get_sanitizer, sanitized_lock
from ..abci import types as abci
from ..trace import NOOP as TRACE_NOOP


def tx_key(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


# below this many txs the native call's fixed overhead beats the win
_NATIVE_HASH_MIN = 4

# async recheck issues its ABCI batches in chunks of this many txs so
# the shared app mutex is released between them (consensus' next
# FinalizeBlock must not queue behind a whole-pool batch)
_RECHECK_CHUNK = 256

# cache-duplicate reject log, shared by the serial path and the
# batch path's intra-batch duplicate resolution (matching on it
# decides whether a duplicate re-enters the next round)
_LOG_CACHE_DUP = "tx already in cache"


def tx_keys(txs: Sequence[bytes]) -> List[bytes]:
    """All tx keys in one pass: the native batch hasher
    (native/wirecodec.cpp sha256_many, same build-on-demand loader the
    merkle tree uses) when available, hashlib otherwise. Bit-identical
    either way — sha256 is sha256."""
    if len(txs) >= _NATIVE_HASH_MIN:
        from ..utils import wirecodec

        nat = wirecodec.module()
        if nat is not None:
            f = getattr(nat, "sha256_many", None)
            if f is not None:
                try:
                    return list(f(txs))
                except Exception:  # pragma: no cover - non-bytes items
                    pass
    sha = hashlib.sha256
    return [sha(t).digest() for t in txs]


class TxCache:
    """LRU of recently seen tx KEYS (reference mempool/cache.go).

    Keyed API: callers hash once (tx_key / tx_keys) and pass the
    32-byte key — the cache never rehashes the full tx."""

    def __init__(self, size: int = 10000):
        self.size = size
        self._od: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = sanitized_lock(
            threading.Lock(), "mempool.txcache"
        )

    def push(self, key: bytes) -> bool:
        """False if already present."""
        with self._lock:
            return self._push_locked(key)

    def _push_locked(self, key: bytes) -> bool:
        if key in self._od:
            self._od.move_to_end(key)
            return False
        self._od[key] = None
        while len(self._od) > self.size:
            self._od.popitem(last=False)
        return True

    def push_many(self, keys: Sequence[bytes]) -> List[bool]:
        """Batch push under ONE lock acquisition; duplicates within
        the batch reject exactly like sequential pushes would."""
        with self._lock:
            return [self._push_locked(k) for k in keys]

    def remove(self, key: bytes) -> None:
        with self._lock:
            self._od.pop(key, None)

    def has(self, key: bytes) -> bool:
        with self._lock:
            return key in self._od


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height when entering the pool
    gas_wanted: int = 0
    senders: set = field(default_factory=set)


class Mempool:
    """Interface (reference mempool/mempool.go Mempool)."""

    # tracing plane (trace/): the node build swaps in the per-node
    # tracer; class-level NOOP keeps every flavor's call sites
    # unconditional
    tracer = TRACE_NOOP

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        raise NotImplementedError

    def check_tx_batch(
        self, txs: List[bytes], senders: Optional[List[str]] = None
    ) -> List[abci.ResponseCheckTx]:
        """Default: the serial path per tx (flavors without a batched
        ingest plane stay correct)."""
        if senders is None:
            senders = [""] * len(txs)
        return [self.check_tx(t, s) for t, s in zip(txs, senders)]

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        raise NotImplementedError

    def update(self, height, txs, results) -> None:
        raise NotImplementedError

    def lock(self):
        raise NotImplementedError

    def unlock(self):
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def txs_available(self) -> threading.Event:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def iter_txs(self) -> List[bytes]:
        raise NotImplementedError


class CListMempool(Mempool):
    def __init__(
        self,
        proxy_app,
        height: int = 0,
        cache_size: int = 10000,
        max_tx_bytes: int = 1024 * 1024,
        max_txs: int = 5000,
        recheck: bool = True,
        notify: Optional[Callable[[], None]] = None,
        async_recheck: bool = False,
    ):
        self.proxy = proxy_app
        self.height = height
        self.cache = TxCache(cache_size)
        self.pool: "OrderedDict[bytes, MempoolTx]" = OrderedDict()
        # monotonic insertion log: gossip routines keep a per-peer seq
        # cursor instead of rescanning the pool (the reference's clist
        # waiter, mempool/reactor.go:217)
        self._seq = 0
        self._log: List[tuple] = []  # (seq, tx_key), insertion order
        self.max_tx_bytes = max_tx_bytes
        self.max_txs = max_txs
        self.recheck = recheck
        self.async_recheck = async_recheck
        self._lock = sanitized_lock(
            threading.RLock(), "mempool.pool"
        )
        self._txs_available = threading.Event()
        self._notify = notify
        # async-recheck state, all guarded by self._lock: keys of the
        # current recheck snapshot (masked from reap), the generation
        # the snapshot belongs to (bumped every update/flush so a
        # superseded recheck drops its verdicts wholesale), and the
        # lazily-built single-thread executor the recheck runs on
        self._recheck_pending: set = set()
        self._recheck_gen = 0
        self._recheck_executor = None

    # --- ingress ------------------------------------------------------

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        tr = self.tracer
        if not tr.enabled:
            return self._check_tx(tx, sender)
        with tr.span("mempool.insert", tid="mempool", bytes=len(tx)) as sp:
            res = self._check_tx(tx, sender)
            sp.set(ok=res.is_ok())
        # unlocked len read (like update's counter): a size() here
        # would re-take the pool lock once per tx just for the stamp
        tr.counter("mempool.size", len(self.pool), tid="mempool")
        return res

    def _check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        if len(tx) > self.max_tx_bytes:
            return abci.ResponseCheckTx(code=1, log="tx too large")
        key = tx_key(tx)
        if not self.cache.push(key):
            with self._lock:
                return self._cache_dup_locked(key, sender)
        res = self.proxy.check_tx(abci.RequestCheckTx(tx=tx))
        with self._lock:
            res = self._admit_locked(tx, key, sender, res)
        if res.is_ok():
            self._txs_available.set()
            if self._notify:
                self._notify()
        return res

    def _cache_dup_locked(
        self, key: bytes, sender: str
    ) -> abci.ResponseCheckTx:
        """Cache-duplicate reject; caller holds self._lock. Records
        the extra sender for gossip echo suppression."""
        if sender:
            mt = self.pool.get(key)
            if mt is not None:
                mt.senders.add(sender)
        return abci.ResponseCheckTx(code=1, log=_LOG_CACHE_DUP)

    def _admit_locked(
        self, tx: bytes, key: bytes, sender: str, res: abci.ResponseCheckTx
    ) -> abci.ResponseCheckTx:
        """Post-ABCI pool insertion; caller holds self._lock."""
        if res.is_ok():
            if len(self.pool) >= self.max_txs:
                self.cache.remove(key)
                return abci.ResponseCheckTx(code=1, log="mempool full")
            mt = MempoolTx(tx=tx, height=self.height, gas_wanted=res.gas_wanted)
            if sender:
                mt.senders.add(sender)
            self.pool[key] = mt
            self._seq += 1
            self._log.append((self._seq, key))
            # txs_available is set by the CALLER — once per tx on the
            # serial path, once per BATCH on the batched one (Event.set
            # takes a condition lock + notify_all; per-item it was ~25%
            # of the serial ingest wall)
        else:
            self.cache.remove(key)
        return res

    def check_tx_batch(
        self, txs: List[bytes], senders: Optional[List[str]] = None
    ) -> List[abci.ResponseCheckTx]:
        """Batched ingest: hash all keys in one native pass, precheck
        under one cache lock, ONE check_tx_batch ABCI call for the
        survivors (per-tx fallback inside _proxy_check_tx_batch),
        admit under one pool lock. Verdicts are identical to running
        check_tx serially over the same txs."""
        n = len(txs)
        if n == 0:
            return []
        if senders is None:
            senders = [""] * n
        with self.tracer.span(
            "mempool.batch", tid="mempool", txs=n
        ) as sp:
            out: List[Optional[abci.ResponseCheckTx]] = [None] * n
            remaining: List[int] = []
            for i, tx in enumerate(txs):
                if len(tx) > self.max_tx_bytes:
                    out[i] = abci.ResponseCheckTx(
                        code=1, log="tx too large"
                    )
                else:
                    remaining.append(i)
            keys: Dict[int, bytes] = dict(
                zip(remaining, tx_keys([txs[i] for i in remaining]))
            )
            n_ok = n_checked = 0
            # Round-based so verdicts are EXACTLY serial-equivalent
            # even with intra-batch duplicates: the first occurrence
            # of a key processes this round; later occurrences wait
            # on its verdict — a cache-removing outcome (app reject /
            # pool full) means the serial loop would have re-checked
            # the duplicate through the app, so it re-enters the next
            # round. Real workloads resolve in one round; a batch of
            # k identical rejected txs degrades to k rounds, i.e. to
            # the serial cost, never worse.
            while remaining:
                first_of: Dict[bytes, int] = {}
                round_items: List[int] = []
                deferred: List[int] = []
                for i in remaining:
                    if keys[i] in first_of:
                        deferred.append(i)
                    else:
                        first_of[keys[i]] = i
                        round_items.append(i)
                fresh = self.cache.push_many(
                    [keys[i] for i in round_items]
                )
                dups: List[int] = []
                pending: List[int] = []
                for i, f in zip(round_items, fresh):
                    (pending if f else dups).append(i)
                results = (
                    self._proxy_check_tx_batch(
                        [abci.RequestCheckTx(tx=txs[i]) for i in pending]
                    )
                    if pending
                    else []
                )
                n_checked += len(pending)
                remaining = []
                with self._lock:
                    for i in dups:
                        out[i] = self._cache_dup_locked(
                            keys[i], senders[i]
                        )
                    for i, res in zip(pending, results):
                        out[i] = self._admit_locked(
                            txs[i], keys[i], senders[i], res
                        )
                        if out[i].is_ok():
                            n_ok += 1
                    for i in deferred:
                        pres = out[first_of[keys[i]]]
                        if pres.is_ok() or pres.log == _LOG_CACHE_DUP:
                            out[i] = self._cache_dup_locked(
                                keys[i], senders[i]
                            )
                        else:
                            remaining.append(i)
            if n_ok:
                self._txs_available.set()
                if self._notify:
                    self._notify()
            sp.set(ok=n_ok, checked=n_checked)
        self.tracer.counter("mempool.size", len(self.pool), tid="mempool")
        return out  # type: ignore[return-value]

    def _proxy_check_tx_batch(
        self, reqs: List[abci.RequestCheckTx]
    ) -> List[abci.ResponseCheckTx]:
        """One batched ABCI call when the proxy supports the fork
        extension, an automatic per-tx fallback loop otherwise
        (mirrors how InsertTx/ReapTxs degrade in abci/types.py)."""
        fn = getattr(self.proxy, "check_tx_batch", None)
        if fn is not None:
            try:
                res = fn(reqs)
            except NotImplementedError:
                res = None
            if res is not None:
                if len(res) != len(reqs):
                    # a short list would silently zip-truncate
                    # verdicts downstream (None entries, unresolved
                    # ingest futures) — fail the batch loudly instead
                    raise RuntimeError(
                        "check_tx_batch returned "
                        f"{len(res)} responses for {len(reqs)} requests"
                    )
                return res
        return [self.proxy.check_tx(r) for r in reqs]

    # --- egress -------------------------------------------------------

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        out, total_b, total_g = [], 0, 0
        with self.tracer.span("mempool.reap", tid="mempool") as sp:
            with self._lock:
                pending = self._recheck_pending
                for k, mt in self.pool.items():
                    if pending and k in pending:
                        # recheck verdict still in flight: a proposer
                        # must not include a tx the app may be about
                        # to invalidate post-commit
                        continue
                    nb = total_b + len(mt.tx)
                    ng = total_g + mt.gas_wanted
                    if max_bytes >= 0 and nb > max_bytes:
                        break
                    if max_gas >= 0 and ng > max_gas:
                        break
                    out.append(mt.tx)
                    total_b, total_g = nb, ng
            sp.set(txs=len(out), bytes=total_b)
        return out

    def iter_txs(self) -> List[bytes]:
        with self._lock:
            return [mt.tx for mt in self.pool.values()]

    def tx_senders(self, key: bytes):
        """Peers that gave us this tx (gossip echo suppression,
        reference mempool/reactor.go broadcastTxRoutine)."""
        with self._lock:
            mt = self.pool.get(key)
            return set(mt.senders) if mt else ()

    def txs_after(self, seq: int) -> List[tuple]:
        """(seq, tx, senders) for pooled txs inserted after `seq` —
        the per-peer gossip cursor."""
        import bisect

        with self._lock:
            i = bisect.bisect_right(self._log, seq, key=lambda e: e[0])
            out = []
            for s, k in self._log[i:]:
                mt = self.pool.get(k)
                if mt is not None:
                    out.append((s, mt.tx, set(mt.senders)))
            return out

    def size(self) -> int:
        with self._lock:
            return len(self.pool)

    def recheck_pending(self) -> int:
        """Txs masked from reap while their recheck is in flight."""
        with self._lock:
            return len(self._recheck_pending)

    # --- post-commit --------------------------------------------------

    def lock(self):
        self._lock.acquire()

    def unlock(self):
        self._lock.release()

    def update(self, height: int, txs: List[bytes], results) -> None:
        """Called with the mempool LOCKED, between FinalizeBlock and
        releasing consensus (reference clist_mempool.go:583). With
        async_recheck the recheck leaves the critical section: wall
        time here no longer scales with the pooled tx count."""
        # loop-affinity: commit-path entry; first caller adopts
        # ownership (analysis/runtime.py, docs/LINT.md)
        san = get_sanitizer()
        if san.enabled:
            san.touch_adopt("mempool.pool")
        self.height = height
        committed_keys = tx_keys(txs) if txs else []
        for key, res in zip(committed_keys, results):
            if res.is_ok():
                self.cache.push(key)  # keep committed txs in cache
            else:
                self.cache.remove(key)
            self.pool.pop(key, None)
        # any in-flight recheck is stale the moment a block commits:
        # bump the generation so its verdicts are dropped wholesale
        # and reset the reap mask (re-populated if we re-snapshot)
        self._recheck_gen += 1
        self._recheck_pending = set()
        scheduled = False
        if self.recheck and self.pool:
            if self.async_recheck:
                scheduled = self._schedule_recheck(height)
            else:
                self._recheck_txs()
        if len(self._log) > 4 * len(self.pool) + 1024:
            self._log = [e for e in self._log if e[1] in self.pool]
        if scheduled:
            # availability decided when the verdicts land (the whole
            # pool is masked right now); an empty pool can't happen
            # here — recheck only scheduled when self.pool is truthy
            pass
        elif self.pool:
            self._txs_available.set()
            if self._notify:
                self._notify()
        else:
            self._txs_available.clear()
        self.tracer.counter("mempool.size", len(self.pool), tid="mempool")

    def _recheck_txs(self) -> None:
        """Synchronous recheck (async_recheck off): one batched ABCI
        call for the whole pool, still inside the consensus critical
        section."""
        snapshot = [(k, self.pool[k].tx) for k in self.pool.keys()]
        results = self._proxy_check_tx_batch(
            [
                abci.RequestCheckTx(tx=tx, type_=abci.CHECK_TX_TYPE_RECHECK)
                for _, tx in snapshot
            ]
        )
        for (k, _), res in zip(snapshot, results):
            if not res.is_ok():
                mt = self.pool.pop(k, None)
                if mt is not None:
                    self.cache.remove(k)

    def _schedule_recheck(self, height: int) -> bool:
        """Snapshot the pool, mask it from reap, and hand the batch to
        the background executor. Caller holds self._lock (update runs
        inside the consensus critical section)."""
        snapshot = [(k, mt.tx) for k, mt in self.pool.items()]
        self._recheck_pending = {k for k, _ in snapshot}
        ex = self._recheck_executor
        if ex is None:
            from concurrent.futures import ThreadPoolExecutor

            ex = self._recheck_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="mempool-recheck"
            )
        ex.submit(self._run_recheck, self._recheck_gen, height, snapshot)
        return True

    def _run_recheck(
        self, gen: int, height: int, snapshot: List[Tuple[bytes, bytes]]
    ) -> None:
        """Background half of the async recheck. Height/generation
        guarded: if another update (or flush) landed while an ABCI
        chunk was in flight, the remaining verdicts are stale — the
        newer update's own recheck owns the pool — so they are
        dropped wholesale and the pending mask is left to the newer
        owner. The snapshot is rechecked in CHUNKS so the shared app
        mutex is released between them: one whole-pool batch would
        head-of-line-block the next height's FinalizeBlock for the
        full recheck wall (the stall this plane exists to kill).
        Verdicts apply per chunk, so reap unmasks progressively."""
        try:
            with self.tracer.span(
                "mempool.recheck", tid="mempool",
                txs=len(snapshot), height=height,
            ) as sp:
                removed = 0
                for lo in range(0, len(snapshot), _RECHECK_CHUNK):
                    chunk = snapshot[lo:lo + _RECHECK_CHUNK]
                    try:
                        results = self._proxy_check_tx_batch(
                            [
                                abci.RequestCheckTx(
                                    tx=tx,
                                    type_=abci.CHECK_TX_TYPE_RECHECK,
                                )
                                for _, tx in chunk
                            ]
                        )
                    except Exception:
                        # app unreachable mid-recheck: fail open
                        # (keep these txs, unmask them) — the next
                        # update rechecks again
                        traceback.print_exc()
                        results = [abci.ResponseCheckTx()] * len(chunk)
                    with self._lock:
                        if gen != self._recheck_gen or height != self.height:
                            sp.set(stale=True)
                            return
                        for (k, _), res in zip(chunk, results):
                            self._recheck_pending.discard(k)
                            if not res.is_ok():
                                mt = self.pool.pop(k, None)
                                if mt is not None:
                                    self.cache.remove(k)
                                    removed += 1
                with self._lock:
                    if gen != self._recheck_gen or height != self.height:
                        sp.set(stale=True)
                        return
                    self._recheck_pending = set()
                    has_txs = bool(self.pool)
                    # availability decided UNDER the lock: a clear()
                    # outside it could clobber the event a concurrent
                    # admission just set
                    if has_txs:
                        self._txs_available.set()
                    else:
                        self._txs_available.clear()
                sp.set(removed=removed)
            if has_txs and self._notify:
                self._notify()
            self.tracer.counter(
                "mempool.size", len(self.pool), tid="mempool"
            )
        except Exception:  # pragma: no cover - belt and braces
            # executor futures swallow exceptions silently; a recheck
            # crash must at least leave a trace and unmask the pool
            traceback.print_exc()
            with self._lock:
                if gen == self._recheck_gen:
                    self._recheck_pending = set()

    def txs_available(self) -> threading.Event:
        return self._txs_available

    def flush(self) -> None:
        with self._lock:
            self.pool.clear()
            self._recheck_gen += 1  # abort any in-flight recheck
            self._recheck_pending = set()
            self._txs_available.clear()


class NopMempool(Mempool):
    """ADR-111: mempool disabled (reference mempool/nop_mempool.go)."""

    def check_tx(self, tx, sender=""):
        return abci.ResponseCheckTx(code=1, log="mempool disabled")

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return []

    def update(self, height, txs, results):
        pass

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self):
        return 0

    def txs_available(self) -> threading.Event:
        return threading.Event()  # never set

    def flush(self):
        pass

    def iter_txs(self):
        return []


class AppMempool(Mempool):
    """Fork feature: the application owns the pool; the node only relays
    InsertTx / ReapTxs (reference mempool/app_mempool.go:23-50) with a
    TTL'd dedup guard in front (internal/guard)."""

    def __init__(self, proxy_app, guard_ttl_s: float = 60.0, guard_size: int = 100_000):
        from ..utils.guard import TTLGuard

        self.proxy = proxy_app
        self.guard = TTLGuard(ttl_s=guard_ttl_s, max_size=guard_size)
        self._txs_available = threading.Event()

    def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        if not self.guard.check_and_set(tx_key(tx)):
            return abci.ResponseCheckTx(code=1, log="duplicate (guard)")
        ok = self.proxy.insert_tx(tx)
        if ok:
            self._txs_available.set()
        return abci.ResponseCheckTx(
            code=abci.CODE_TYPE_OK if ok else 1
        )

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return self.proxy.reap_txs(max_bytes, max_gas)

    def iter_txs(self):
        return []  # the app owns the pool; nothing to walk

    def update(self, height, txs, results):
        self._txs_available.clear()

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self):
        return -1  # unknown: app-owned

    def txs_available(self) -> threading.Event:
        return self._txs_available

    def flush(self):
        pass

    def iter_txs(self):
        return self.proxy.reap_txs(-1, -1)
