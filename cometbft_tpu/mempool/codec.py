"""Channel-0x30 tx gossip framing: length-prefixed batches with
single-tx backward compatibility.

Historically a mempool message WAS the raw tx bytes. Batching needs a
frame, so batch messages open with a 2-byte magic followed by a
varint tx count and length-prefixed txs:

    MAGIC(2) | uvarint(count>=1) | { uvarint(len) | tx }*count

Compatibility contract, both directions:

- ``encode_txs([tx])`` emits the RAW tx (old wire form) unless the tx
  itself begins with MAGIC, in which case it is escaped as a batch of
  one — so a new receiver can always tell the two apart.
- ``decode_txs`` treats anything not starting with MAGIC as a raw
  single tx, and falls back to raw-single-tx on ANY parse failure
  after the magic — an old peer relaying a tx that happens to begin
  with the magic bytes still gets through (a malformed-but-magic
  message then fails CheckTx like any garbage tx would).
"""

from __future__ import annotations

from typing import List

# 0x30 echoes the mempool channel id; 0xB7 is arbitrary non-ASCII
MAGIC = b"\xb7\x30"

# decode hard caps: a frame is at most one channel message (1 MiB
# descriptor), so anything claiming more items than bytes is garbage
_MAX_BATCH_TXS = 1 << 20


def _put_uvarint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _read_uvarint(buf: bytes, pos: int) -> "tuple[int, int]":
    shift = 0
    val = 0
    while True:
        if pos >= len(buf) or shift > 63:
            raise ValueError("truncated/overlong varint")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def encode_batch(txs: List[bytes]) -> bytes:
    """Always-framed batch (len >= 1)."""
    if not txs:
        raise ValueError("empty tx batch")
    out = bytearray(MAGIC)
    _put_uvarint(out, len(txs))
    for tx in txs:
        _put_uvarint(out, len(tx))
        out += tx
    return bytes(out)


def encode_txs(txs: List[bytes]) -> bytes:
    """Wire form for a gossip send: raw bytes for a lone
    non-magic-prefixed tx (old wire form, old peers keep working),
    a batch frame otherwise."""
    if len(txs) == 1 and not txs[0].startswith(MAGIC):
        return txs[0]
    return encode_batch(txs)


def decode_txs(msg: bytes) -> List[bytes]:
    """Txs carried by one channel-0x30 message (see module doc)."""
    if not msg.startswith(MAGIC):
        return [msg]
    try:
        pos = len(MAGIC)
        count, pos = _read_uvarint(msg, pos)
        if count < 1 or count > min(_MAX_BATCH_TXS, len(msg)):
            raise ValueError("implausible batch count")
        txs = []
        for _ in range(count):
            ln, pos = _read_uvarint(msg, pos)
            if pos + ln > len(msg):
                raise ValueError("truncated tx")
            txs.append(msg[pos:pos + ln])
            pos += ln
        if pos != len(msg):
            raise ValueError("trailing bytes after batch")
        return txs
    except ValueError:
        # old peer relaying a raw tx that starts with our magic
        return [msg]
