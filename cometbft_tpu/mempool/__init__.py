from .mempool import (  # noqa: F401
    AppMempool,
    CListMempool,
    Mempool,
    NopMempool,
    TxCache,
)
