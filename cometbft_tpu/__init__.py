"""cometbft_tpu — a TPU-native BFT consensus framework.

A ground-up re-design of the capabilities of CometBFT (the production fork of
Tendermint Core; reference: sujae-yu/cometbft) for TPU hosts:

- Orchestration (consensus rounds, p2p, storage, ABCI) is Python/asyncio —
  the reference's Go logic is I/O-bound control flow.
- The compute kernels — ed25519 batch signature verification (point
  decompression, double-scalar multiplication, SHA-512), SHA-256/merkle
  hashing — are JAX programs compiled by XLA for TPU, vectorized over
  signature lanes and sharded over device meshes via ``shard_map``.

Package layout:
    ops/        JAX/XLA TPU kernels (field arithmetic, curve ops, hashes)
    parallel/   device-mesh sharding + cross-height batch coalescing
    crypto/     host-side crypto API (keys, signing, batch-verifier dispatch)
    types/      block / vote / commit / validator data model + verification
    models/     replicated applications (ABCI state machines, e.g. kvstore)
    consensus/  the BFT state machine, WAL, replay
    ...         (mempool, p2p, blocksync, light, state, store, node, rpc)
"""

__version__ = "0.1.0"
