"""Host: connection admission for the lp2p stack.

Mirrors the reference's `lp2p/host.go:54-301` responsibilities the TPU
way: a **ResourceManager** caps connections / streams / queued bytes
(go-libp2p's rcmgr), and a **ConnGater** lets the switch veto peers at
dial time, at accept time, and after the handshake proves an identity
(reference `lp2p/host.go:263-301` InterceptPeerDial /
InterceptAccept / InterceptSecured). Transport setup (TCP or
in-memory socketpair) and the secret-connection handshake are shared
with the native stack — the stacks differ above the encrypted
connection, not below it.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional


class ResourceError(Exception):
    pass


class ResourceManager:
    """Static limits; count what is open, refuse past the cap."""

    def __init__(
        self,
        max_conns: int = 128,
        max_streams_per_conn: int = 64,
        stream_queue: int = 256,
    ):
        self.max_conns = max_conns
        self.max_streams_per_conn = max_streams_per_conn
        self.stream_queue = stream_queue
        self.open_conns = 0

    def acquire_conn(self) -> None:
        if self.open_conns >= self.max_conns:
            raise ResourceError(
                f"connection limit reached ({self.max_conns})"
            )
        self.open_conns += 1

    def release_conn(self) -> None:
        self.open_conns = max(0, self.open_conns - 1)


class ConnGater:
    """Pluggable admission callbacks; default allows everything.

    deny lists may be mutated at runtime (ban_peer feeds them)."""

    def __init__(
        self,
        intercept_peer_dial: Optional[Callable[[str], bool]] = None,
        intercept_accept: Optional[Callable[[str], bool]] = None,
        intercept_secured: Optional[Callable[[str], bool]] = None,
    ):
        self.denied_peers: set = set()
        self._dial = intercept_peer_dial
        self._accept = intercept_accept
        self._secured = intercept_secured

    def allow_peer_dial(self, peer_id: Optional[str]) -> bool:
        if peer_id and peer_id in self.denied_peers:
            return False
        return self._dial(peer_id) if (self._dial and peer_id) else True

    def allow_accept(self, conn_str: str) -> bool:
        return self._accept(conn_str) if self._accept else True

    def allow_secured(self, peer_id: str) -> bool:
        if peer_id in self.denied_peers:
            return False
        return self._secured(peer_id) if self._secured else True


class Host:
    """Bundles transport + admission; produces gated, resource-counted
    upgraded connections for the lp2p switch."""

    def __init__(
        self,
        transport,
        rcmgr: Optional[ResourceManager] = None,
        gater: Optional[ConnGater] = None,
    ):
        self.transport = transport
        self.rcmgr = rcmgr or ResourceManager()
        self.gater = gater or ConnGater()

    @property
    def listen_addr(self) -> str:
        return self.transport.listen_addr

    async def listen(self, addr: str = "") -> None:
        await self.transport.listen(addr)

    async def accept(self):
        """Next admitted inbound (sconn, node_info, conn_str)."""
        while True:
            sconn, their_info, conn_str = await self.transport.accept()
            if not self.gater.allow_accept(conn_str):
                sconn.close()
                continue
            if not self.gater.allow_secured(their_info.node_id):
                sconn.close()
                continue
            try:
                self.rcmgr.acquire_conn()
            except ResourceError:
                sconn.close()
                continue
            return sconn, their_info, conn_str

    async def dial(self, addr: str, expected_id: Optional[str] = None):
        if not self.gater.allow_peer_dial(expected_id):
            raise ResourceError(f"gater denied dial to {expected_id}")
        self.rcmgr.acquire_conn()
        try:
            sconn, their_info, conn_str = await self.transport.dial(
                addr, expected_id
            )
        except Exception:
            self.rcmgr.release_conn()
            raise
        if not self.gater.allow_secured(their_info.node_id):
            sconn.close()
            self.rcmgr.release_conn()
            raise ResourceError(
                f"gater denied secured peer {their_info.node_id}"
            )
        return sconn, their_info, conn_str

    def conn_closed(self) -> None:
        self.rcmgr.release_conn()

    async def close(self) -> None:
        # bounded (ASY110): a wedged transport must not hang host close
        try:
            await asyncio.wait_for(self.transport.close(), 5.0)
        except asyncio.TimeoutError:
            pass
