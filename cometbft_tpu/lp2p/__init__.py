"""lp2p: alternative stream-multiplexed p2p stack (fork feature).

The reference fork carries `lp2p/` — a second `p2p.Switcher`
implementation over go-libp2p where every legacy channel byte maps to
its own libp2p protocol/stream (`lp2p/stream.go:28`), with a resource
manager and connection gater (`lp2p/host.go:54-301`), selected by
config at `node/node.go:476-575`.

This package is the TPU-build equivalent, designed rather than ported:
the secret-connection handshake (our Noise) is reused from `p2p.conn`,
and a lightweight yamux-style stream multiplexer gives each reactor
channel an independent stream over the encrypted connection — so a
slow blocksync transfer cannot head-of-line-block consensus votes the
way a single shared MConnection stream could. Reactor messages drain
through the auto-scaling worker pool (`utils.autopool`), matching the
reference's `lp2p/reactor_set.go` draining model.
"""

from .mux import Muxer, MuxStream, MuxError  # noqa: F401
from .host import Host, ConnGater, ResourceManager, ResourceError  # noqa: F401
from .switch import Lp2pSwitch, Lp2pPeer  # noqa: F401
