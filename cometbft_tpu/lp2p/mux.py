"""Stream multiplexer over a SecretConnection (yamux-flavored).

Frames ride inside the encrypted channel; each frame is

    stream_id:u32 | flag:u8 | length:u32 | payload[length]

flags: SYN opens a stream (payload = protocol id, utf-8), DATA carries
one complete message (the mux is message-oriented like the reference's
length-prefixed libp2p streams, not byte-oriented), FIN half-closes,
RST aborts, PING/PONG keep the connection alive. Stream-id parity
avoids open collisions: the connection initiator allocates odd ids,
the accepter even ids (reference analog: yamux under go-libp2p).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Callable, Dict, Optional

from ..utils.log import get_logger

_log = get_logger("lp2p.mux")

SYN, DATA, FIN, RST, PING, PONG = range(6)
_HDR = struct.Struct(">IBI")

MAX_FRAME_PAYLOAD = 16 * 1024 * 1024
DEFAULT_STREAM_QUEUE = 256
PING_INTERVAL_S = 20.0
PONG_TIMEOUT_S = 45.0


class MuxError(Exception):
    pass


class MuxStream:
    """One logical stream: ordered message queue in, writes out via
    the shared muxer."""

    def __init__(self, mux: "Muxer", stream_id: int, protocol: str):
        qsize = getattr(mux, "stream_queue", DEFAULT_STREAM_QUEUE)
        self.mux = mux
        self.stream_id = stream_id
        self.protocol = protocol
        self.recv_q: asyncio.Queue = asyncio.Queue(qsize)
        self.closed = False
        self.reset = False
        self.dropped = 0  # inbound messages discarded on queue overflow

    async def send(self, msg: bytes) -> None:
        if self.closed:
            raise MuxError(f"stream {self.stream_id} closed")
        await self.mux._send_frame(self.stream_id, DATA, msg)

    def try_send(self, msg: bytes) -> bool:
        """Best-effort enqueue; False when the connection's outbound
        queue is saturated (caller drops, matching Peer.try_send)."""
        if self.closed:
            return False
        return self.mux._try_send_frame(self.stream_id, DATA, msg)

    async def recv(self) -> Optional[bytes]:
        """Next message, or None at clean EOF."""
        if self.closed and self.recv_q.empty():
            return None
        msg = await self.recv_q.get()
        return msg  # None sentinel = FIN/RST

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                # bounded (ASY110): the FIN is a courtesy — a dead
                # conn must not hang the stream close
                await asyncio.wait_for(
                    self.mux._send_frame(self.stream_id, FIN, b""), 2.0
                )
            except asyncio.CancelledError:
                raise
            except (asyncio.TimeoutError, OSError, RuntimeError):
                pass  # dead conn: the FIN courtesy just didn't land
            self.mux._drop_stream(self.stream_id)

    def abort(self) -> None:
        if not self.closed:
            self.closed = True
            self.reset = True
            self.mux._try_send_frame(self.stream_id, RST, b"")
            self.mux._drop_stream(self.stream_id)
            try:  # wake a reader blocked on an (empty) queue
                self.recv_q.put_nowait(None)
            except asyncio.QueueFull:
                pass


class Muxer:
    """Multiplexes MuxStreams over one SecretConnection.

    on_stream(stream) fires for every remotely-opened stream after its
    SYN arrives. on_error(exc) fires once when the connection dies.
    """

    def __init__(
        self,
        sconn,
        initiator: bool,
        on_stream: Callable[[MuxStream], None],
        on_error: Optional[Callable[[Exception], None]] = None,
        max_streams: int = 64,
        send_queue: int = 1024,
        send_rate: int = 0,
        recv_rate: int = 0,
        stream_queue: int = DEFAULT_STREAM_QUEUE,
        overflow_fatal: Optional[Callable[[str], bool]] = None,
    ):
        self.sconn = sconn
        self.streams: Dict[int, MuxStream] = {}
        self.on_stream = on_stream
        self.on_error = on_error
        # predicate by protocol id: True -> inbound queue overflow is
        # fatal to the CONNECTION (request/response channels, where a
        # dropped reply stalls the requester until timeout and a
        # stream-level reset would leave the remote's outbound stream
        # dead); False -> count the drop (gossip channels re-send)
        self.overflow_fatal = overflow_fatal or (lambda _proto: False)
        self.max_streams = max_streams
        self.stream_queue = stream_queue
        self._initiator = initiator
        self._next_id = 1 if initiator else 2
        self._send_q: asyncio.Queue = asyncio.Queue(send_queue)
        self._tasks = []
        self._dead = False
        self._pong = asyncio.Event()
        self.sent_bytes = 0
        self.recv_bytes = 0
        # operator bandwidth caps apply to the lp2p stack too (the
        # native stack throttles inside MConnection); 0 = unlimited
        from ..p2p.conn.connection import FlowRate

        self._send_flow = FlowRate(send_rate) if send_rate > 0 else None
        self._recv_flow = FlowRate(recv_rate) if recv_rate > 0 else None

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._send_routine()),
            asyncio.create_task(self._recv_routine()),
            asyncio.create_task(self._ping_routine()),
        ]

    async def stop(self) -> None:
        self._dead = True
        for s in list(self.streams.values()):
            s.closed = True
        self.streams.clear()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                # bounded (ASY110): a routine swallowing its cancel
                # must not wedge stop — the fd close below kills its
                # I/O anyway
                await asyncio.wait_for(t, 2.0)
            except asyncio.CancelledError:
                pass  # we cancelled t ourselves two lines up
            except Exception:
                pass  # routine died on a torn conn; fd close follows
        self.sconn.close()

    # --- stream open --------------------------------------------------

    def _alloc_stream(self, protocol: str) -> MuxStream:
        if self._dead:
            raise MuxError("muxer closed")
        if len(self.streams) >= self.max_streams:
            raise MuxError("stream limit reached")
        sid = self._next_id
        self._next_id += 2
        if sid in self.streams:  # unreachable with parity enforcement
            raise MuxError(f"stream id {sid} already in use")
        st = MuxStream(self, sid, protocol)
        self.streams[sid] = st
        return st

    async def open_stream(self, protocol: str) -> MuxStream:
        st = self._alloc_stream(protocol)
        await self._send_frame(st.stream_id, SYN, protocol.encode())
        return st

    def open_stream_nowait(self, protocol: str) -> MuxStream:
        """Synchronous open: enqueue the SYN without awaiting, so
        callers can hand out usable streams before yielding to the
        loop (peers must be sendable the moment reactors see them).
        Raises MuxError if the send queue is full (only possible on an
        already-saturated connection)."""
        st = self._alloc_stream(protocol)
        if not self._try_send_frame(st.stream_id, SYN, protocol.encode()):
            self._drop_stream(st.stream_id)
            raise MuxError("send queue full during stream open")
        return st

    # --- framing ------------------------------------------------------

    async def _send_frame(self, sid: int, flag: int, payload: bytes):
        if self._dead:
            raise MuxError("muxer closed")
        await self._send_q.put(_HDR.pack(sid, flag, len(payload)) + payload)

    def _try_send_frame(self, sid: int, flag: int, payload: bytes) -> bool:
        if self._dead:
            return False
        try:
            self._send_q.put_nowait(
                _HDR.pack(sid, flag, len(payload)) + payload
            )
            return True
        except asyncio.QueueFull:
            return False

    def _drop_stream(self, sid: int) -> None:
        self.streams.pop(sid, None)

    def _die(self, exc: Exception) -> None:
        if self._dead:
            return
        self._dead = True
        for s in list(self.streams.values()):
            s.closed = True
            try:
                s.recv_q.put_nowait(None)
            except asyncio.QueueFull:
                pass
        if self.on_error:
            self.on_error(exc)

    # --- routines -----------------------------------------------------

    async def _send_routine(self) -> None:
        try:
            while True:
                frame = await self._send_q.get()
                if self._send_flow is not None:
                    await self._send_flow.throttle(len(frame))
                self.sent_bytes += len(frame)
                await self.sconn.write_msg(frame)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._die(e)

    async def _recv_routine(self) -> None:
        # bytearray + consume offset: appending chunks and slicing the
        # head stays O(bytes) per frame (repeated bytes concatenation
        # over ~1KB SecretConnection chunks would be O(n^2))
        buf = bytearray()
        pos = 0
        try:
            while True:
                while len(buf) - pos < _HDR.size:
                    buf += await self._read()
                sid, flag, n = _HDR.unpack_from(buf, pos)
                if n > MAX_FRAME_PAYLOAD:
                    raise MuxError(f"oversized frame ({n} bytes)")
                pos += _HDR.size
                while len(buf) - pos < n:
                    buf += await self._read()
                payload = bytes(buf[pos : pos + n])
                pos += n
                if pos > 1 << 16:
                    del buf[:pos]
                    pos = 0
                self._handle(sid, flag, payload)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._die(e)

    async def _read(self) -> bytes:
        chunk = await self.sconn.read_chunk()
        if not chunk:
            raise MuxError("connection closed")
        if self._recv_flow is not None:
            await self._recv_flow.throttle(len(chunk))
        self.recv_bytes += len(chunk)
        return chunk

    def _handle(self, sid: int, flag: int, payload: bytes) -> None:
        if flag == SYN:
            # a remote-opened stream must carry the REMOTE side's id
            # parity (initiator odd / accepter even); without this a
            # peer could pre-register an id in our allocator's space
            # and cross-wire a later local stream onto its frames
            remote_parity = 0 if self._initiator else 1
            if (
                sid % 2 != remote_parity
                or sid in self.streams
                or len(self.streams) >= self.max_streams
            ):
                self._try_send_frame(sid, RST, b"")
                return
            st = MuxStream(self, sid, payload.decode("utf-8", "replace"))
            self.streams[sid] = st
            try:
                self.on_stream(st)
            except Exception:
                st.abort()
        elif flag == DATA:
            st = self.streams.get(sid)
            if st is None:
                return  # late data on a dropped stream
            try:
                st.recv_q.put_nowait(payload)
            except asyncio.QueueFull:
                st.dropped += 1
                if self.overflow_fatal(st.protocol):
                    # request/response channel: a silently dropped
                    # reply leaves the requester stalled until its
                    # timeout, and a stream-level RST would leave the
                    # remote's outbound stream dead for the rest of the
                    # connection — kill the CONNECTION so the error
                    # surfaces and the switch's reconnect logic
                    # restores a clean channel set (the native stack's
                    # MConnection does the same on queue overflow)
                    _log.error(
                        "inbound queue overflow on request/response "
                        "channel, dropping connection",
                        protocol=st.protocol,
                        stream=sid,
                    )
                    self._die(
                        MuxError(
                            f"inbound overflow on {st.protocol}"
                        )
                    )
                elif st.dropped == 1:
                    # gossip channels re-send: drop, but surface the
                    # first occurrence per stream
                    _log.info(
                        "inbound queue overflow, dropping message",
                        protocol=st.protocol,
                        stream=sid,
                    )
        elif flag in (FIN, RST):
            st = self.streams.pop(sid, None)
            if st is not None:
                st.closed = True
                st.reset = flag == RST
                try:
                    st.recv_q.put_nowait(None)
                except asyncio.QueueFull:
                    pass
        elif flag == PING:
            self._try_send_frame(0, PONG, b"")
        elif flag == PONG:
            self._pong.set()

    async def _ping_routine(self) -> None:
        try:
            while True:
                await asyncio.sleep(PING_INTERVAL_S)
                self._pong.clear()
                self._try_send_frame(0, PING, b"")
                try:
                    await asyncio.wait_for(
                        self._pong.wait(), PONG_TIMEOUT_S
                    )
                except asyncio.TimeoutError:
                    raise MuxError("ping timeout")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._die(e)
