"""Lp2pSwitch: the alternative Switcher over stream-multiplexed conns.

Reference analog: `lp2p.Switch` (`lp2p/switch.go:25,56`) — the second
implementation of `p2p.Switcher` (`p2p/switcher.go:12`) selected by
config at `node/node.go:476-575`. Each legacy channel byte maps to its
own protocol / stream pair (`lp2p/stream.go:28`), and inbound reactor
messages drain through the auto-scaling worker pool
(`lp2p/reactor_set.go` + `internal/autopool`).

Implementation note: peer lifecycle (dial, reconnect-with-backoff,
ban, reactor registry, broadcast) is shared with the native Switch by
subclassing — both stacks satisfy the same Switcher contract and only
differ in what is layered over the encrypted connection (per-channel
mux streams here, a single MConnection there) and in admission (Host
gater + resource manager here). In particular the persistent-peer
reconnect path backs off through the one shared policy in
utils/backoff.py (exponential + full jitter + cap) rather than a
second hand-rolled schedule.
"""

from __future__ import annotations

import asyncio
import traceback
from typing import Any, Dict, List, Optional

from ..p2p.node_info import NodeInfo
from ..p2p.switch import Switch
from .host import ConnGater, Host, ResourceManager
from .mux import Muxer, MuxStream

PROTOCOL_PREFIX = "/cometbft/ch/"


def channel_protocol(chan_id: int) -> str:
    """Legacy channel byte -> protocol id (reference lp2p/stream.go:28)."""
    return f"{PROTOCOL_PREFIX}{chan_id:#04x}"


def protocol_channel(protocol: str) -> Optional[int]:
    if not protocol.startswith(PROTOCOL_PREFIX):
        return None
    try:
        return int(protocol[len(PROTOCOL_PREFIX):], 16)
    except ValueError:
        return None


# Channels whose messages are request/response pairs (blocksync block
# responses 0x40, statesync snapshot 0x60 / chunk 0x61 responses): a
# reply dropped on inbound-queue overflow would stall the requester
# until its timeout, so overflow is FATAL TO THE CONNECTION — the peer
# drops and (if persistent) reconnects with a clean channel set. A
# stream-level reset would leave the remote's outbound stream dead for
# the connection's lifetime; gossip channels keep drop semantics.
REQRESP_CHANNELS = frozenset({0x40, 0x60, 0x61})


def _overflow_fatal(protocol: str) -> bool:
    return protocol_channel(protocol) in REQRESP_CHANNELS


class Lp2pPeer:
    """Peer over a Muxer: one outbound stream per registered channel
    (opened at start), inbound streams dispatched by protocol id.
    Interface-compatible with p2p.Peer."""

    def __init__(
        self,
        sconn,
        node_info: NodeInfo,
        conn_str: str,
        channels: List[tuple],  # (chan_id, priority, max_msg_size)
        on_receive,  # (chan_id, msg, peer)
        on_error=None,  # (peer, exc)
        outbound: bool = False,
        persistent: bool = False,
        max_streams: int = 64,
        stream_queue: int = 0,
        send_rate: int = 0,
        recv_rate: int = 0,
    ):
        self.node_info = node_info
        self.conn_str = conn_str
        self.outbound = outbound
        self.persistent = persistent
        self._data: Dict[str, Any] = {}
        self._on_receive = on_receive
        self._on_error = on_error
        self._max_msg_size = {c[0]: c[2] for c in channels}
        self._chan_ids = [c[0] for c in channels]
        self._out: Dict[int, MuxStream] = {}
        self._ready = asyncio.Event()
        self._reader_tasks: List[asyncio.Task] = []
        self._start_task: Optional[asyncio.Task] = None
        self._stopped = False
        from .mux import DEFAULT_STREAM_QUEUE

        self.mux = Muxer(
            sconn,
            initiator=outbound,
            on_stream=self._on_stream,
            on_error=self._mux_error,
            max_streams=max_streams,
            stream_queue=stream_queue or DEFAULT_STREAM_QUEUE,
            send_rate=send_rate,
            recv_rate=recv_rate,
            overflow_fatal=_overflow_fatal,
        )

    # --- identity -----------------------------------------------------

    @property
    def peer_id(self) -> str:
        return self.node_info.node_id

    def __repr__(self) -> str:
        return f"Lp2pPeer({self.peer_id[:10]}@{self.conn_str})"

    # --- lifecycle ----------------------------------------------------

    def start(self) -> None:
        self.mux.start()
        # open channel streams synchronously (SYNs enqueue without
        # awaiting): reactors call add_peer right after start() and
        # must be able to try_send immediately — e.g. statesync's
        # one-shot snapshots request would otherwise be silently lost
        try:
            for cid in self._chan_ids:
                self._out[cid] = self.mux.open_stream_nowait(
                    channel_protocol(cid)
                )
            self._ready.set()
        except Exception as e:
            self._mux_error(e)

    async def stop(self) -> None:
        self._stopped = True
        if self._start_task:
            self._start_task.cancel()
        for t in self._reader_tasks:
            t.cancel()
        try:
            # bounded (ASY110): mux.stop is internally bounded; this
            # keeps a hung conn from wedging the whole switch stop
            await asyncio.wait_for(self.mux.stop(), 5.0)
        except asyncio.TimeoutError:
            pass

    def abort(self) -> None:
        """Synchronous last-resort close (never awaits): see
        p2p MConnection.abort — an abandoned stop must still kill the
        underlying fd or the remote keeps a zombie peer entry."""
        self._stopped = True
        if self._start_task:
            self._start_task.cancel()
        for t in self._reader_tasks:
            t.cancel()
        for t in self.mux._tasks:
            t.cancel()
        try:
            self.mux.sconn.close()
        except Exception:
            pass

    def _mux_error(self, exc: Exception) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._on_error:
            self._on_error(self, exc)

    def inject_error(self, exc: Exception) -> None:
        """Chaos hook (parity with p2p.Peer.inject_error): die as if
        ``exc`` came from a mux routine, driving the switch's
        on_error -> reconnect path."""
        self._mux_error(exc)

    # --- inbound ------------------------------------------------------

    def _on_stream(self, st: MuxStream) -> None:
        cid = protocol_channel(st.protocol)
        if cid is None or cid not in self._max_msg_size:
            st.abort()
            return
        # drop finished readers first: streams come and go for the
        # peer's whole lifetime, and a done task kept in the list is
        # a leak the complexity pass (ASY119) flags
        self._reader_tasks = [
            t for t in self._reader_tasks if not t.done()
        ]
        self._reader_tasks.append(
            asyncio.create_task(self._read_stream(cid, st))
        )

    async def _read_stream(self, cid: int, st: MuxStream) -> None:
        limit = self._max_msg_size[cid]
        try:
            while True:
                msg = await st.recv()
                if msg is None:
                    return
                if len(msg) > limit:
                    raise ValueError(
                        f"message exceeds channel {cid:#x} limit {limit}"
                    )
                self._on_receive(cid, msg, self)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._mux_error(e)

    # --- outbound -----------------------------------------------------

    async def send(self, chan_id: int, msg: bytes) -> bool:
        try:
            await asyncio.wait_for(self._ready.wait(), 10.0)
            await self._out[chan_id].send(msg)
            return True
        except asyncio.CancelledError:
            raise  # peer stop cancels senders; never report "sent"
        except Exception:
            return False

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        st = self._out.get(chan_id)
        if st is None:
            return False  # streams still opening
        return st.try_send(msg)

    # --- traffic totals (uniform across peer implementations) ---------

    @property
    def recv_total(self) -> int:
        return self.mux.recv_bytes

    @property
    def send_total(self) -> int:
        return self.mux.sent_bytes

    # --- per-peer reactor state ---------------------------------------

    def get(self, key: str, default=None):
        return self._data.get(key, default)

    def set(self, key: str, value) -> None:
        self._data[key] = value


class Lp2pSwitch(Switch):
    """Switcher implementation over Host + Muxer.

    Defaults to autopool draining (the reference's lp2p reactor set
    always drains through autopool workers)."""

    def __init__(
        self,
        transport,
        node_info: NodeInfo,
        max_peers: int = 50,
        rcmgr: Optional[ResourceManager] = None,
        gater: Optional[ConnGater] = None,
        use_autopool: bool = True,
        send_rate: int = 0,
        recv_rate: int = 0,
        reconnect_config: Optional[dict] = None,
    ):
        host = Host(transport, rcmgr=rcmgr, gater=gater)
        super().__init__(
            host, node_info, max_peers=max_peers,
            use_autopool=use_autopool,
            reconnect_config=reconnect_config,
        )
        self.host = host
        self.send_rate = send_rate
        self.recv_rate = recv_rate

    def _discard_conn(self, sconn) -> None:
        # the Host admitted this conn (rcmgr.acquire_conn); a rejection
        # above the Host must release the slot or churn from banned /
        # duplicate peers permanently exhausts admission capacity
        super()._discard_conn(sconn)
        self.host.conn_closed()

    def _make_peer(
        self, sconn, their_info, conn_str, outbound, persistent=False
    ) -> Lp2pPeer:
        channels = [
            (d.chan_id, d.priority, d.max_msg_size)
            for d in self.channel_descs
        ]
        peer = Lp2pPeer(
            sconn,
            their_info,
            conn_str,
            channels,
            on_receive=self._on_peer_msg,
            on_error=self._on_peer_error,
            outbound=outbound,
            persistent=persistent
            or their_info.node_id in self.persistent_addrs,
            max_streams=self.host.rcmgr.max_streams_per_conn,
            stream_queue=self.host.rcmgr.stream_queue,
            send_rate=self.send_rate,
            recv_rate=self.recv_rate,
        )
        self._register_peer(peer)
        return peer

    async def _remove_peer(self, peer, exc, reconnect=False) -> None:
        present = self.peers.get(peer.peer_id) is peer
        await super()._remove_peer(peer, exc, reconnect)
        if present:
            self.host.conn_closed()

    def _evict_peer_sync(self, peer, reason) -> None:
        # duplicate-resolution loser: release its admission slot like
        # _remove_peer does, or incarnation churn leaks rcmgr capacity
        present = self.peers.get(peer.peer_id) is peer
        super()._evict_peer_sync(peer, reason)
        if present:
            self.host.conn_closed()

    def ban_peer(self, peer_id: str) -> None:
        self.host.gater.denied_peers.add(peer_id)
        super().ban_peer(peer_id)
