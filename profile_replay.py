"""Profile the blocksync replay HOST pipeline (VERDICT r3 #7).

Runs a bounded replay over the cached bench corpus with the host
verify backend under cProfile and prints the per-stage breakdown, so
the next replay lever is chosen from data (docs/PERF.md records the
findings). Usage:

    python profile_replay.py [n_blocks=1500] [window=128]
"""

import asyncio
import cProfile
import io
import os
import pstats
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    n_blocks = int(sys.argv[1]) if len(sys.argv) > 1 else 1500
    window = int(sys.argv[2]) if len(sys.argv) > 2 else 128

    import bench
    from cometbft_tpu.blocksync import BlockSyncReactor
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.node.inprocess import build_node
    from cometbft_tpu.utils.chaingen import StorePeerClient

    crypto_batch.set_default_backend("cpu")
    gen, privs, parts = bench._corpus(
        int(os.environ.get("BENCH_REPLAY_BLOCKS", "10000"))
    )

    cfg = test_config(".")
    cfg.base.db_backend = "memdb"
    fresh = build_node(gen, None, config=cfg)

    async def run():
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
            verify_window=window,
        )
        reactor.pool.set_peer_range(
            "src", StorePeerClient(parts), 1, n_blocks
        )
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 3600)
        await reactor.stop()
        return reactor.blocks_applied

    prof = cProfile.Profile()
    t0 = time.time()
    prof.enable()
    applied = asyncio.run(run())
    prof.disable()
    wall = time.time() - t0
    print(
        f"replayed {applied} blocks in {wall:.1f}s "
        f"({applied / wall:.1f} blocks/s, host backend, "
        f"window={window})\n"
    )
    for sort, title, n in (
        ("cumulative", "BY CUMULATIVE TIME", 35),
        ("tottime", "BY SELF TIME", 35),
    ):
        out = io.StringIO()
        st = pstats.Stats(prof, stream=out)
        st.sort_stats(sort).print_stats(n)
        print(f"===== {title} =====")
        body = out.getvalue()
        # keep header + rows, drop the noise preamble
        print("\n".join(body.splitlines()[4:]))


if __name__ == "__main__":
    main()
